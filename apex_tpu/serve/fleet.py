"""Fault-tolerant serving fleet — replica health, failover re-dispatch,
hedged requests, rolling drain.

One :class:`~apex_tpu.serve.scheduler.ServeScheduler` is a single point
of failure: PR 8's warm restart survives a fatal *tick*, but a dead
*replica* (process gone, host gone, network gone) still takes every
in-flight request with it. This module is the control plane above N
single-chip engine replicas — thread-backed, ``ThreadProcessGroup``-style,
so CPU tier-1 can fake a pod — composing the pieces the repo already
owns:

- **Replica registry + heartbeat health model**
  (:class:`ReplicaRegistry`) — each replica's worker thread beats a
  monotonic-clock heartbeat (``perf_counter`` deltas only, apexlint
  APX005); the router's sweep escalates watchdog-style on missed beats:
  ``healthy → suspect`` at ``suspect_misses`` heartbeat intervals of
  silence (``serve_replica_suspect``), ``→ dead`` at ``dead_misses``
  (``serve_replica_dead``). A beat heals a *suspect* back to healthy; a
  *dead* replica never self-revives — the router has already re-dispatched
  its requests, and a partition that heals must rejoin through an
  explicit :meth:`FleetController.restart_replica`, never by quietly
  beating again (the double-complete door stays closed).
- **Router** (:class:`FleetController`) — least-loaded dispatch over
  healthy replicas (suspects only as a fallback pool), bounded retry
  with exponential backoff for retriable replica-side rejections, and
  optional **hedged dispatch**: a request with no terminal status after
  ``hedge_ms`` fires one copy on a second replica
  (``serve_hedge_fired``); the first terminal status wins, the loser is
  aborted, and exactly-once is enforced by request id — a terminal
  record is accepted only for the request's *currently live* attempt
  object, so a superseded or duplicate completion can never settle
  twice. Routing also sheds on PR-10 burn rates: a replica whose SLO
  short-window burn is at or above ``shed_burn_factor`` receives new
  load only when every alternative is burning too.
- **Failover re-dispatch** — a dead replica's live requests are
  re-submitted to survivors (``serve_failover``, with the span the
  request lost on the dead replica as a timed goodput cause) and
  re-prefilled through the existing bucketed prefill — bit-exact by the
  PR-5 prefill/decode invariant, so greedy outputs are bit-identical to
  a no-fault run, and a prefix-cached survivor pays only the unshared
  tail. Sampled streams restart their (per-replica, seeded) PRNG path —
  the per-replica ``sampling_state`` journal (PR 8) still covers
  same-replica warm restarts bit-for-bit.
- **Draining / rolling restart** — :meth:`FleetController.drain` marks a
  replica draining (no new admissions), migrates its still-queued
  requests to peers through the scheduler's :meth:`pop_queued` hook
  (no bogus terminal status — the fleet record stays exactly-once),
  lets in-flight requests finish, then ``serve_replica_drained``;
  :meth:`restart_replica` resets the engine (compiled artifacts kept —
  zero recompiles) and rejoins it (``serve_replica_restarted``).
  :meth:`rolling_restart` does this one replica at a time, so admitting
  capacity never drops below N-1 (tier-1 asserts the recorded minimum).
- **Fleet chaos** — :class:`~apex_tpu.resilience.fault_injection.FaultInjector`
  grows ``kill_replica`` (the worker dies mid-loop, heartbeats stop),
  ``partition_replica`` (heartbeats AND results stop crossing, the
  replica keeps decoding — the no-double-complete case when it heals),
  and ``straggler_replica`` (per-tick stalls — what drives hedging).
  The tier-1 smoke runs all three in one seeded schedule and asserts
  every submitted request reaches exactly one terminal status
  fleet-wide, greedy completions bit-identical to the no-fault fleet,
  and zero decode retraces on every surviving replica.

**Request journeys** (PR 13). With a :class:`~apex_tpu.monitor.trace.
Tracer` armed (``tracer=``), the controller opens ONE fleet-level trace
per request — ``journey`` root with ``fleet_queue → attempt[replica=k]
→ backoff → hedge → failover → terminal`` children — and propagates the
trace id + attempt span id into each replica attempt
(:attr:`~apex_tpu.serve.scheduler.Request.trace_id` /
``trace_parent``), so the replica scheduler's existing
``queue/prefill/decode`` spans nest as children of the attempt. Every
fleet span is stamped from the SAME clock reads the summary and the
``serve_failover`` events use, and carries the rounded
``seconds``/``ttft_s``/``latency_s`` values as attrs — span durations
reconcile EXACTLY with TTFT/latency/failover accounting
(``tools/trace_explain.py`` exits 1 when they don't), and decode still
compiles once per replica with tracing armed. The journey root closes
LAST, after every bus event for the request — the tail-capture router's
fallback decision point. :class:`FleetTraceHarness` wires the whole
surface for the CLIs: per-replica Chrome-trace files at ``PATH.rK``,
the fleet-plane file at ``PATH``, and the
:class:`~apex_tpu.monitor.trace.TailCaptureRouter` head-sampling +
tail-capture policy across them.

**Threading contract.** Each replica's worker thread touches only its
own scheduler (which serializes under its own lock) and the registry
(every row mutation under the registry lock — apexlint APX002 keeps the
discipline). All :class:`FleetController` methods — ``submit``, ``run``,
``pump``, ``drain``, ``restart_replica`` — are driven from ONE control
thread; the controller's own tables need no lock because no worker ever
writes them (workers signal through the registry and their scheduler's
``done`` list, which the control thread harvests under the scheduler
lock). The pump's per-iteration probes are **lock-free**: each worker
publishes a ``(load, done_count)`` snapshot after every tick (one tuple
rebind — the ``partitioned``/``crashed`` APX002-legal snapshot idiom,
PR 11's documented follow-up), and the control thread refreshes it
itself after its own submits/pops, so routing and the harvest gate
never contend with the scheduler lock ``step()`` holds across a tick —
the hedge/failover reaction latency no longer waits out the slowest
replica's in-flight tick. Only an actual harvest (new terminal records
exist) or an explicit drain/restart takes a scheduler lock from the
control thread.

**Metrics.** Give each :class:`EngineReplica` its own
:class:`~apex_tpu.serve.metrics.ServeMetrics`: per-replica snapshots fold
through ``tools/metrics_merge.py`` (the PR-10 exact merge) into one
fleet view whose counters reconcile exactly with the fleet summary's
``attempts`` section (tier-1 asserts). See docs/serving.md "Fleet
failover and draining".
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set

from apex_tpu.monitor.export import percentile
from apex_tpu.monitor.flight import FlightRecorder
# module-level on purpose (flight too): a function-local import inside
# FleetTraceHarness would RE-import monitor.trace after
# test_chip_worker's sys.modules purge, binding a fresh module whose
# bus the collection-time scheduler modules never publish to — the
# tail-capture router would then miss every lifecycle event (the
# test_serve_resilience subscribe-at-collection precedent)
from apex_tpu.monitor.trace import (ChromeTraceWriter, TailCaptureRouter,
                                    Tracer)
from apex_tpu.serve.scheduler import Request, ServeScheduler
from apex_tpu.utils.logging import publish_event

# replica lifecycle states (docs/serving.md has the state diagram):
# healthy -> suspect -> dead on missed heartbeats (suspect heals on a
# beat; dead is absorbing until restart_replica); healthy -> draining ->
# drained -> healthy is the rolling-restart path
REPLICA_HEALTHY = "healthy"
REPLICA_SUSPECT = "suspect"
REPLICA_DRAINING = "draining"
REPLICA_DRAINED = "drained"
REPLICA_DEAD = "dead"

# states the heartbeat sweep may escalate (drained replicas idle-beat;
# dead ones are already as escalated as it gets)
_SWEEPABLE = (REPLICA_HEALTHY, REPLICA_SUSPECT, REPLICA_DRAINING)
# states the router will send NEW admissions to (healthy preferred;
# suspect only as the fallback pool)
ADMITTING_STATES = (REPLICA_HEALTHY, REPLICA_SUSPECT)


class ReplicaRegistry:
    """Heartbeat-driven replica health: monotonic beats in, watchdog-style
    escalation events out.

    ``heartbeat`` is called from every replica's worker thread;
    ``sweep``/``set_state`` from the fleet's control thread — every row
    mutation holds the registry lock (APX002). Events are published
    OUTSIDE the lock (the bus delivers to arbitrary subscribers; the
    same snapshot-then-deliver rule the bus itself follows)."""

    def __init__(self, heartbeat_s: float = 0.05, *,
                 suspect_misses: float = 2.0, dead_misses: float = 4.0,
                 clock=time.perf_counter):
        if heartbeat_s <= 0:
            raise ValueError(f"heartbeat_s must be > 0: {heartbeat_s}")
        if not 0 < suspect_misses < dead_misses:
            raise ValueError(
                f"need 0 < suspect_misses < dead_misses, got "
                f"{suspect_misses} / {dead_misses}")
        self.heartbeat_s = float(heartbeat_s)
        self.suspect_misses = float(suspect_misses)
        self.dead_misses = float(dead_misses)
        self.clock = clock
        self._lock = threading.Lock()
        self._rows: Dict[str, Dict[str, Any]] = {}

    def register(self, replica_id: str) -> None:
        with self._lock:
            self._rows[str(replica_id)] = {
                "state": REPLICA_HEALTHY, "last_beat": self.clock(),
                "beats": 0}

    def heartbeat(self, replica_id: str) -> None:
        """One beat from the replica's worker thread. Heals a *suspect*
        back to healthy; a *dead* row keeps its state — a healed
        partition's beats must not quietly re-admit a replica whose
        requests were already re-dispatched (restart_replica is the only
        way back in)."""
        with self._lock:
            row = self._rows[str(replica_id)]
            row["last_beat"] = self.clock()
            row["beats"] += 1
            if row["state"] == REPLICA_SUSPECT:
                row["state"] = REPLICA_HEALTHY

    def touch_all(self) -> None:
        """Refresh every row's beat stamp (fleet start: the gap between
        construction and the first worker beat must not read as misses)."""
        with self._lock:
            now = self.clock()
            for row in self._rows.values():
                row["last_beat"] = now

    def sweep(self, now: Optional[float] = None
              ) -> List[Dict[str, Any]]:
        """Escalate silent replicas; returns (and publishes) the
        transition records. Exactly one ``serve_replica_suspect`` /
        ``serve_replica_dead`` per transition — dead is absorbing, so a
        storm of sweeps cannot re-announce a death."""
        now = self.clock() if now is None else now
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            for rid, row in self._rows.items():
                if row["state"] not in _SWEEPABLE:
                    continue
                age = now - row["last_beat"]
                misses = age / self.heartbeat_s
                if misses >= self.dead_misses:
                    transitions.append({
                        "replica": rid, "old": row["state"],
                        "new": REPLICA_DEAD,
                        "misses": round(misses, 2),
                        "age_s": round(age, 6)})
                    row["state"] = REPLICA_DEAD
                elif misses >= self.suspect_misses \
                        and row["state"] == REPLICA_HEALTHY:
                    transitions.append({
                        "replica": rid, "old": REPLICA_HEALTHY,
                        "new": REPLICA_SUSPECT,
                        "misses": round(misses, 2),
                        "age_s": round(age, 6)})
                    row["state"] = REPLICA_SUSPECT
        for t in transitions:
            event = ("serve_replica_dead" if t["new"] == REPLICA_DEAD
                     else "serve_replica_suspect")
            publish_event(event, level="warning", replica=t["replica"],
                          misses=t["misses"], age_s=t["age_s"])
        return transitions

    def state(self, replica_id: str) -> str:
        with self._lock:
            return self._rows[str(replica_id)]["state"]

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {rid: row["state"]
                    for rid, row in self._rows.items()}

    def row(self, replica_id: str) -> Dict[str, Any]:
        """A copy of one replica's registry row plus its beat age — the
        context a per-replica flight recorder stamps into a death
        postmortem (state, last heartbeat, how long it was silent)."""
        with self._lock:
            row = dict(self._rows[str(replica_id)])
        row["replica"] = str(replica_id)
        row["age_s"] = round(self.clock() - row["last_beat"], 6)
        return row

    def set_state(self, replica_id: str, state: str, *,
                  beat: bool = False) -> None:
        """Explicit lifecycle transition (drain / drained / restart) from
        the control thread; ``beat=True`` also refreshes the stamp so a
        just-restarted replica is not instantly re-suspected."""
        with self._lock:
            row = self._rows[str(replica_id)]
            row["state"] = state
            if beat:
                row["last_beat"] = self.clock()


class EngineReplica:
    """One engine + scheduler + worker thread: a fake pod member.

    The worker loop per tick: consult the fault injector (kill /
    partition / straggle), heartbeat the registry (unless partitioned),
    run one scheduler tick, sleep briefly when idle. ``partitioned`` and
    ``crashed`` are plain boolean rebinds (worker writes, control thread
    reads — the snapshot idiom, no read-modify-write); everything else
    the worker touches is behind the scheduler or registry lock."""

    ROLES = ("unified", "prefill", "decode")

    def __init__(self, replica_id: str, engine, *, admission=None,
                 metrics=None, tracer=None, idle_sleep_s: float = 0.002,
                 role: str = "unified"):
        self.replica_id = str(replica_id)
        self.engine = engine
        self.metrics = metrics
        self.scheduler = ServeScheduler(engine, admission=admission,
                                        metrics=metrics, tracer=tracer)
        self.idle_sleep_s = float(idle_sleep_s)
        if role not in self.ROLES:
            raise ValueError(
                f"role={role!r} must be one of {self.ROLES}")
        # disaggregated serving role (docs/serving.md "Disaggregated
        # prefill/decode"): "prefill" replicas run prompt prefill and
        # stream committed KV pages out, "decode" replicas receive pages
        # and serve the client stream, "unified" does both (the
        # non-disaggregated default — FleetController ignores roles)
        self.role = role
        # committed-but-undelivered page handoffs sourced at this
        # replica — control-thread-only bookkeeping (the disaggregation
        # controller is single-threaded by the FleetController contract);
        # a draining prefill replica may not report drained while > 0
        self.pending_handoffs = 0
        self.index = 0              # assigned by the controller (tiebreak)
        self.done_seen = 0          # harvest cursor into scheduler.done
        self.tick = 0
        self.partitioned = False
        self.crashed = False
        # lock-free (load, done_count) snapshot: the worker rebinds it
        # after every tick, the control thread after its own submits and
        # pops — one tuple rebind, the APX002-legal snapshot idiom — so
        # the pump's routing/harvest probes never contend with the
        # scheduler lock step() holds across a whole tick
        self._progress = (0, 0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registry: Optional[ReplicaRegistry] = None
        self._injector = None

    @property
    def reachable(self) -> bool:
        """Results can cross to the router: not crashed (memory gone)
        and not behind a partition (nothing crosses until it heals)."""
        return not self.crashed and not self.partitioned

    def start(self, registry: ReplicaRegistry, injector=None) -> None:
        self._registry = registry
        self._injector = injector
        self.publish_progress()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._worker, name=f"replica-{self.replica_id}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=10.0)
        self._thread = None

    def restart(self) -> None:
        """Clean restart after drain (or death): stop the worker, drop
        any stale live requests WITHOUT touching the engine
        (their fleet copies were already migrated or re-dispatched; the
        router's attempt-identity dedup drops the stale records), reset
        the engine state — compiled artifacts kept, zero recompiles —
        and start a fresh worker."""
        self.stop()
        if self.scheduler.load() > 0:
            # only a dead replica restarts non-empty; a drained one is
            # idle by definition
            self.scheduler.drain_and_reject("engine_failure")
        self.engine.reset()
        self.tick = 0
        self.partitioned = False
        self.crashed = False
        self.publish_progress()
        if self._registry is not None:
            self.start(self._registry, self._injector)

    def publish_progress(self) -> None:
        """Refresh the lock-free progress snapshot (one scheduler-lock
        acquisition, one tuple rebind). The worker calls it each tick;
        the control thread calls it right after its own scheduler
        mutations (submit / pop_queued / abort), so :meth:`load` is
        exact whenever the controller just changed it and at most one
        tick stale otherwise."""
        self._progress = self.scheduler.progress()

    def load(self) -> int:
        """Queued + in-slot requests — the router's load signal. Reads
        the published snapshot, never the scheduler lock."""
        return self._progress[0]

    @property
    def done_count(self) -> int:
        """Published terminal-record count — the harvest gate: the
        controller takes the scheduler lock only when this moved past
        its cursor."""
        return self._progress[1]

    def burn_short_max(self) -> float:
        """The replica's worst SLO short-window burn rate (0.0 with no
        SLO armed) — the PR-10 routing signal: a replica burning its
        error budget at or above the fleet's shed factor receives new
        load only when every alternative burns too."""
        m = self.metrics
        if m is None or m.slo is None:
            return 0.0
        with self.scheduler._lock:  # the SLO windows move under it
            summary = m.slo.summary()
        return max((s["burn_short"] for s in summary.values()),
                   default=0.0)

    # ------------------------------------------------------- worker loop
    def _worker(self) -> None:
        from apex_tpu.resilience.fault_injection import SimulatedCrash

        try:
            while not self._stop.is_set():
                self.tick += 1
                inj = self._injector
                if inj is not None:
                    if inj.replica_kill_due(self.replica_id, self.tick):
                        raise SimulatedCrash(
                            f"replica {self.replica_id} killed at tick "
                            f"{self.tick}")
                    stall = inj.replica_straggle_due(self.replica_id,
                                                     self.tick)
                    if stall:
                        time.sleep(stall)
                    self.partitioned = inj.replica_partitioned(
                        self.replica_id, self.tick)
                if not self.partitioned:
                    self._registry.heartbeat(self.replica_id)
                busy = self.scheduler.step()
                self.publish_progress()
                if not busy:
                    time.sleep(self.idle_sleep_s)
        except SimulatedCrash:
            # the process is gone: heartbeats stop, the registry sweep
            # escalates, and the router re-dispatches the live requests.
            # Unharvested results die with the memory (`reachable`).
            self.crashed = True


class _FleetRequest:
    """Router-side bookkeeping for one client request: the immutable
    spec, the live attempt per replica, and the exactly-once terminal
    record (first terminal of a live attempt wins)."""

    __slots__ = ("spec", "attempts", "attempt_t", "record", "dispatch_t",
                 "hedged", "retries", "next_dispatch_t", "spans",
                 "attempt_seq")

    def __init__(self, spec: Request):
        self.spec = spec
        self.attempts: Dict[str, Request] = {}
        self.attempt_t: Dict[str, float] = {}
        self.record: Optional[Dict[str, Any]] = None
        self.dispatch_t: Optional[float] = None
        self.hedged = False
        self.retries = 0
        self.next_dispatch_t = 0.0
        # journey spans (tracer armed only): "root", "fleet_queue",
        # "backoff", and ("attempt", replica_id) entries
        self.spans: Optional[Dict[Any, Any]] = None
        self.attempt_seq = 0


@dataclasses.dataclass
class FleetStats:
    """Fleet-wide accounting: exactly one record per submitted request,
    plus the attempt-level counters the per-replica metrics snapshots
    must reconcile with after ``tools/metrics_merge.py``."""

    requests: List[Dict[str, Any]]
    replicas: int
    failovers: int
    hedge_fired: int
    migrations: int
    retries: int
    replica_dead: int
    replica_restarted: int
    attempts: Dict[str, int]
    per_replica: Dict[str, Dict[str, Any]]
    decode_step_s: List[float]
    wall_s: float

    def summary(self) -> Dict[str, Any]:
        new_tokens = sum(r["new_tokens"] for r in self.requests)
        ttfts = [r["ttft_s"] for r in self.requests if "ttft_s" in r]
        lat = list(self.decode_step_s)
        return {
            "requests": len(self.requests),
            "completed": sum(r["state"] == "completed"
                             for r in self.requests),
            "evicted": sum(r["state"] == "evicted"
                           for r in self.requests),
            "rejected": sum(r["state"] == "rejected"
                            for r in self.requests),
            "deadline_exceeded": sum(
                r.get("finish_reason") == "deadline"
                for r in self.requests),
            "shed_rate": round(
                sum(r["state"] == "rejected" for r in self.requests)
                / len(self.requests), 4) if self.requests else 0.0,
            # fleet resilience counters (all lower-is-better; the
            # regression gate knows failover/hedge_fired/replica_dead)
            "failovers": self.failovers,
            "hedge_fired": self.hedge_fired,
            "migrations": self.migrations,
            "retries": self.retries,
            "replica_dead": self.replica_dead,
            "replica_restarted": self.replica_restarted,
            "replicas": self.replicas,
            # attempt-level counters: what the merged per-replica
            # metrics snapshots must equal, family by family
            "attempts": dict(self.attempts),
            "decode_steps": len(lat),     # pooled over every replica
            "new_tokens": new_tokens,
            # fleet throughput is wall-clock rate (replicas decode in
            # parallel — summing per-replica decode-time rates would
            # overstate a straggling fleet)
            "tokens_per_s": round(new_tokens / self.wall_s, 3)
            if self.wall_s else 0.0,
            "p50_step_ms": round(percentile(lat, 0.50) * 1e3, 3),
            "p99_step_ms": round(percentile(lat, 0.99) * 1e3, 3),
            "ttft_p50_ms": round(percentile(ttfts, 0.50) * 1e3, 3),
            "ttft_p99_ms": round(percentile(ttfts, 0.99) * 1e3, 3),
            "wall_s": round(self.wall_s, 6),
        }


class FleetController:
    """Route a request stream over N engine replicas with health-driven
    failover, optional hedging, and rolling drain.

    Drive it from one control thread: :meth:`submit` the workload, then
    :meth:`run` (which starts the replica workers, pumps the control
    loop until every request has its terminal record, and stops the
    workers). :meth:`pump` is public for embeddings that interleave
    control actions (drain, chaos healing) with the loop — the tier-1
    tests do exactly that."""

    def __init__(self, replicas: Sequence[EngineReplica], *,
                 heartbeat_ms: float = 50.0,
                 suspect_misses: float = 2.0, dead_misses: float = 4.0,
                 hedge_ms: Optional[float] = None,
                 max_retries: int = 2, retry_backoff_s: float = 0.01,
                 retry_backoff_factor: float = 2.0,
                 max_retry_backoff_s: float = 0.5,
                 shed_burn_factor: float = 2.0,
                 fault_injector=None, tracer=None,
                 clock=time.perf_counter):
        if not replicas:
            raise ValueError("FleetController needs at least one replica")
        ids = [h.replica_id for h in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        if hedge_ms is not None and len(replicas) < 2:
            raise ValueError(
                "hedged dispatch needs >= 2 replicas: a hedge fired at "
                "the only replica would race itself")
        if hedge_ms is not None and hedge_ms <= 0:
            raise ValueError(f"hedge_ms must be > 0: {hedge_ms}")
        self.handles = list(replicas)
        for i, h in enumerate(self.handles):
            h.index = i
        self._by_id = {h.replica_id: h for h in self.handles}
        self.registry = ReplicaRegistry(
            heartbeat_ms / 1e3, suspect_misses=suspect_misses,
            dead_misses=dead_misses, clock=clock)
        for h in self.handles:
            self.registry.register(h.replica_id)
        self.hedge_ms = hedge_ms
        self.max_retries = max(0, int(max_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_factor = float(retry_backoff_factor)
        self.max_retry_backoff_s = float(max_retry_backoff_s)
        self.shed_burn_factor = float(shed_burn_factor)
        self.injector = fault_injector
        # fleet-level request journeys: one trace per submitted request,
        # stamped from the same clock reads the accounting uses
        self.tracer = tracer if tracer is not None and tracer.enabled \
            else None
        self._clock = clock
        self._pump_interval_s = min(0.003, heartbeat_ms / 4e3)
        self._requests: Dict[Any, _FleetRequest] = {}
        self._pending: List[_FleetRequest] = []
        self._started = False
        self._draining_all = False
        self._drain_shed_done = False
        self._drain_migrated: Dict[str, int] = {}
        self._t0: Optional[float] = None
        # fleet counters (the summary + bench entry carry them)
        self.dispatches = 0
        self.failovers = 0
        self.hedges_fired = 0
        self.migrations = 0
        self.retries = 0
        self.replica_deaths = 0
        self.replica_restarts = 0
        self._min_admitting = len(self.handles)

    # ----------------------------------------------------------- intake
    def submit(self, spec: Request) -> bool:
        """Accept one client request (the object is the immutable SPEC —
        per-replica attempts are fresh copies, so a hedge or failover
        can never alias scheduler state across replicas) and dispatch it
        to the least-loaded admitting replica. Returns ``False`` when
        the fleet is draining (SIGTERM drain: no new admissions).
        Malformed requests raise — caller errors, not load."""
        if self._draining_all:
            return False
        if spec.request_id in self._requests:
            raise ValueError(
                f"request id {spec.request_id!r} already submitted "
                f"fleet-wide (exactly-once needs unique ids)")
        if not len(spec.tokens):
            raise ValueError(f"request {spec.request_id!r}: empty prompt")
        max_len = self.handles[0].engine.max_len
        if len(spec.tokens) >= max_len:
            raise ValueError(
                f"request {spec.request_id!r}: prompt of "
                f"{len(spec.tokens)} tokens leaves no room to generate "
                f"under max_len={max_len}")
        freq = _FleetRequest(spec)
        self._requests[spec.request_id] = freq
        now = self._clock()
        if self.tracer is not None:
            # the journey roots at the controller's OWN submit stamp —
            # the same `now` every dispatch/backoff computation below
            # measures from, so fleet span durations and the routing
            # accounting are the same numbers
            root = self.tracer.begin(
                "journey", trace_id=f"journey:{spec.request_id}",
                t0=now, request_id=str(spec.request_id),
                prompt_tokens=len(spec.tokens))
            freq.spans = {
                "root": root,
                "fleet_queue": self.tracer.begin("fleet_queue",
                                                 parent=root, t0=now)}
        self._dispatch_new(freq, now)
        return True

    def _dispatch_new(self, freq: _FleetRequest, now: float) -> None:
        """First dispatch of a fresh request: route or pend. The
        disaggregation controller overrides this seam to interpose a
        prefill→decode page handoff before the real dispatch."""
        handle = self._route()
        if handle is None:
            freq.next_dispatch_t = now
            self._pending.append(freq)
        else:
            self._submit_attempt(freq, handle, now)

    def begin_drain(self) -> None:
        """Fleet-wide drain (the ``--drain-on SIGTERM`` contract): stop
        accepting new work; the next :meth:`pump` sheds every
        still-QUEUED (never admitted) request as a terminal retriable
        rejection (``finish_reason="draining"`` — a healthy fleet can
        serve it), in-flight requests finish, then :meth:`run` returns
        normally. Safe at signal depth: this is one flag write — the
        control thread does the actual shedding."""
        self._draining_all = True

    # ---------------------------------------------------------- routing
    def _route(self, exclude: Sequence[str] = ()
               ) -> Optional[EngineReplica]:
        """Least-loaded admitting replica: healthy before suspect,
        burn-rate-quiet before shedding, then load, then index (a
        deterministic tiebreak)."""
        states = self.registry.states()
        cands = [h for h in self.handles
                 if h.replica_id not in exclude and not h.crashed
                 and states.get(h.replica_id) in ADMITTING_STATES]
        if not cands:
            return None
        healthy = [h for h in cands
                   if states[h.replica_id] == REPLICA_HEALTHY]
        pool = healthy or cands
        quiet = [h for h in pool
                 if h.burn_short_max() < self.shed_burn_factor]
        pool = quiet or pool
        return min(pool, key=lambda h: (h.load(), h.index))

    def _submit_attempt(self, freq: _FleetRequest,
                        handle: EngineReplica, now: float) -> None:
        spec = freq.spec
        att = Request(request_id=spec.request_id,
                      tokens=list(spec.tokens),
                      max_new_tokens=spec.max_new_tokens,
                      eos_id=spec.eos_id, deadline_ms=spec.deadline_ms,
                      priority=spec.priority, tenant=spec.tenant)
        sp = freq.spans
        if sp is not None:
            # whichever wait preceded this dispatch ends now (first
            # dispatch: fleet_queue; a retry: its backoff span)
            for key in ("fleet_queue", "backoff"):
                waited = sp.pop(key, None)
                if waited is not None:
                    self.tracer.end(waited, t1=now)
            freq.attempt_seq += 1
            att_span = self.tracer.begin(
                "attempt", parent=sp["root"], t0=now,
                replica=handle.replica_id, attempt=freq.attempt_seq)
            sp[("attempt", handle.replica_id)] = att_span
            # propagate: the replica scheduler's request trace nests
            # under this attempt span, in the SAME journey trace
            att.trace_id = sp["root"].trace_id
            att.trace_parent = att_span.span_id
        freq.attempts[handle.replica_id] = att
        freq.attempt_t[handle.replica_id] = now
        freq.dispatch_t = now
        self.dispatches += 1
        # a False return (admission reject) leaves a terminal rejected
        # record in the replica's done list — the harvest/retry path
        # owns it from there
        handle.scheduler.submit(att)
        handle.publish_progress()

    # ------------------------------------------------------ control loop
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._t0 = self._clock()
        # the gap between construction (engine builds, test setup) and
        # this point must not count as missed beats
        self.registry.touch_all()
        for h in self.handles:
            h.start(self.registry, self.injector)

    def stop(self) -> None:
        for h in self.handles:
            h.stop()
        self._started = False

    def pump(self) -> None:
        """One control iteration: sweep heartbeats (failover on a death
        transition), harvest reachable replicas' terminal records,
        dispatch pending/retrying requests, fire due hedges. Public so
        embeddings (and the chaos tests) can interleave control actions
        with the loop."""
        now = self._clock()
        for t in self.registry.sweep(now):
            if t["new"] == REPLICA_DEAD:
                self.replica_deaths += 1
                self._failover(t["replica"], now)
        if self._draining_all and not self._drain_shed_done:
            self._drain_shed_done = True
            self._shed_queued_for_drain(now)
        self._harvest(now)
        self._dispatch_pending(now)
        self._fire_hedges(now)
        states = self.registry.states()
        for h in self.handles:
            # a draining replica whose last in-flight request just left
            # becomes drained HERE, whichever loop is pumping — so a
            # drain(wait=False) can never wedge it in draining forever
            if states.get(h.replica_id) == REPLICA_DRAINING:
                self._maybe_mark_drained(h)
        admitting = sum(s in ADMITTING_STATES for s in states.values())
        self._min_admitting = min(self._min_admitting, admitting)

    def run(self, *, max_wall_s: float = 60.0) -> FleetStats:
        """Start the workers (if not already), pump until every
        submitted request has exactly one terminal record, stop the
        workers, return the stats. ``max_wall_s`` is a loud liveness
        bound — a wedged fleet raises instead of hanging tier-1."""
        self.start()
        t0 = self._clock()
        try:
            while not self.all_terminal():
                self.pump()
                if self._clock() - t0 > max_wall_s:
                    live = [rid for rid, f in self._requests.items()
                            if f.record is None]
                    raise TimeoutError(
                        f"fleet did not settle {len(live)} request(s) "
                        f"within {max_wall_s}s: {live[:8]}")
                time.sleep(self._pump_interval_s)
        finally:
            self.stop()
        return self.stats()

    def all_terminal(self) -> bool:
        return all(f.record is not None
                   for f in self._requests.values())

    # ---------------------------------------------------------- harvest
    def _harvest(self, now: float) -> None:
        for handle in self.handles:
            if not handle.reachable:
                # a crashed replica's unharvested results died with its
                # memory; a partitioned one's cannot cross until it
                # heals (and then lose first-terminal-wins if the
                # router already settled the request elsewhere)
                continue
            if handle.done_count == handle.done_seen:
                # lock-free gate: the published snapshot says nothing
                # new is terminal — skip the scheduler lock entirely
                # (it may be held across a multi-second contended tick)
                continue
            done, handle.done_seen = handle.scheduler.done_since(
                handle.done_seen)
            for req in done:
                self._settle(handle, req, now)

    def _settle(self, handle: EngineReplica, req: Request,
                now: float) -> None:
        freq = self._requests.get(req.request_id)
        if freq is None:
            return      # replica-local traffic (e.g. an injector storm)
        if freq.record is not None:
            return      # hedge/partition duplicate: first terminal won
        if freq.attempts.get(handle.replica_id) is not req:
            # a superseded attempt (failed over, migrated, or drained
            # after death) — its record must never settle the request
            return
        del freq.attempts[handle.replica_id]
        done_t = req.done_t if req.done_t is not None else now
        if req.state == "rejected":
            # a shed copy must never settle a request another replica
            # is actively serving: with a hedge copy still live, that
            # copy IS the retry — drop this rejection outright (if the
            # live copy is later rejected too, attempts is empty and
            # the normal retry/terminal path below owns it)
            if freq.attempts:
                self._end_attempt(freq, handle.replica_id, t1=done_t,
                                  status="cancelled", reason="rejected")
                return
            if self._retryable(freq):
                freq.retries += 1
                self.retries += 1
                backoff = min(
                    self.retry_backoff_s
                    * self.retry_backoff_factor ** (freq.retries - 1),
                    self.max_retry_backoff_s)
                freq.next_dispatch_t = now + backoff
                self._end_attempt(freq, handle.replica_id, t1=done_t,
                                  status="cancelled", reason="rejected")
                if freq.spans is not None:
                    # the wait until re-dispatch: closed by the next
                    # _submit_attempt (its `now` — the same stamp
                    # attempt_t records)
                    freq.spans["backoff"] = self.tracer.begin(
                        "backoff", parent=freq.spans["root"], t0=now,
                        retry=freq.retries,
                        backoff_s=round(backoff, 6))
                self._pending.append(freq)
                return
        self._accept(freq, handle.replica_id, req, now)

    def _retryable(self, freq: _FleetRequest) -> bool:
        return freq.retries < self.max_retries \
            and self._route() is not None

    def _accept(self, freq: _FleetRequest, replica_id: str,
                req: Request, now: float) -> None:
        """First terminal of a live attempt wins: record it, abort every
        other live attempt (reachable replicas only — an unreachable
        one's duplicate is dropped at harvest by the attempt-identity
        rule), then close the journey — terminal + root spans last,
        after every lifecycle event the settle published."""
        record = dict(req.record())
        record["replica"] = replica_id
        freq.record = record
        for rid, att in list(freq.attempts.items()):
            h = self._by_id[rid]
            if h.reachable:
                h.scheduler.abort(att.request_id)
                h.publish_progress()
            self._end_attempt(freq, rid, t1=now, status="cancelled",
                              reason="superseded")
        freq.attempts.clear()
        done_t = req.done_t if req.done_t is not None else now
        self._end_attempt(
            freq, replica_id, t1=done_t,
            status="ok" if req.state == "completed" else "cancelled")
        self._close_journey(freq, t1=done_t, record=record)

    # ------------------------------------------------------ journey spans
    def _end_attempt(self, freq: _FleetRequest, replica_id: str, *,
                     t1: float, status: str, **attrs: Any) -> None:
        sp = freq.spans
        if sp is None:
            return
        span = sp.pop(("attempt", replica_id), None)
        if span is not None:
            self.tracer.end(span, t1=t1, status=status, **attrs)

    def _close_journey(self, freq: _FleetRequest, *, t1: float,
                       record: Dict[str, Any]) -> None:
        """Terminal marker + root close, carrying the record's EXACT
        rounded ttft/latency values as attrs (what trace_explain
        reconciles bit-for-bit against the summary). Runs exactly once,
        LAST — after every bus event for this request — so the
        tail-capture router's fallback decision sees a settled world."""
        sp = freq.spans
        if sp is None:
            return
        freq.spans = None
        # anything still open (a dead replica's attempt that never
        # settled, a backoff that never re-dispatched) ends here
        for key, span in list(sp.items()):
            if key != "root":
                self.tracer.end(span, t1=t1, status="cancelled")
        attrs = {"state": record["state"],
                 "finish_reason": record.get("finish_reason"),
                 "replica": record.get("replica"),
                 "new_tokens": record.get("new_tokens", 0)}
        for key in ("ttft_s", "latency_s"):
            if record.get(key) is not None:
                attrs[key] = record[key]
        term = self.tracer.begin("terminal", parent=sp["root"], t0=t1,
                                 **attrs)
        self.tracer.end(term, t1=t1)
        self.tracer.end(
            sp["root"], t1=t1,
            status="ok" if record["state"] == "completed"
            else "cancelled", **attrs)

    # --------------------------------------------------------- failover
    def _failover(self, replica_id: str, now: float) -> None:
        """A replica was declared dead: every one of its live requests
        with no other live attempt is re-dispatched to a survivor
        (``serve_failover``; the span the request already spent on the
        dead replica is the timed loss — the survivor redoes that
        work, bit-identically under greedy decoding)."""
        for freq in self._requests.values():
            att = freq.attempts.pop(replica_id, None)
            if att is None or freq.record is not None:
                continue
            lost_t0 = freq.attempt_t.get(replica_id, now)
            lost_s = max(now - lost_t0, 0.0)
            seconds = round(lost_s, 6)
            self._end_attempt(freq, replica_id, t1=now, status="error",
                              cause="replica_dead", seconds=seconds)
            if freq.attempts:
                continue    # a hedge copy already runs elsewhere
            self.failovers += 1
            target = self._route(exclude=(replica_id,))
            publish_event(
                "serve_failover", level="warning",
                request_id=freq.spec.request_id,
                from_replica=replica_id,
                to_replica=target.replica_id if target else None,
                cause="replica_dead", seconds=seconds)
            if freq.spans is not None:
                # the failover gap span covers EXACTLY the lost attempt
                # window, and its ``seconds`` attr is the SAME rounded
                # value the event (and so the goodput ledger) carries —
                # the reconciliation in tools/trace_explain.py is exact
                fo = self.tracer.begin(
                    "failover", parent=freq.spans["root"], t0=lost_t0,
                    from_replica=replica_id,
                    to_replica=target.replica_id if target else None,
                    cause="replica_dead", seconds=seconds)
                self.tracer.end(fo, t1=now)
            if target is not None:
                self._submit_attempt(freq, target, now)
            else:
                freq.next_dispatch_t = now
                self._pending.append(freq)

    def _dispatch_pending(self, now: float) -> None:
        still: List[_FleetRequest] = []
        for freq in self._pending:
            if freq.record is not None:
                continue    # settled while waiting (a late duplicate)
            if freq.next_dispatch_t > now:
                still.append(freq)
                continue
            handle = self._route()
            if handle is None:
                if all(s == REPLICA_DEAD
                       for s in self.registry.states().values()):
                    # total fleet loss: exactly-once still stands — a
                    # synthetic terminal eviction, never a silent drop
                    self._fail_terminal(freq, now)
                else:
                    still.append(freq)   # draining/restarting: wait
                continue
            self._submit_attempt(freq, handle, now)
        self._pending = still

    def _fail_terminal(self, freq: _FleetRequest, now: float) -> None:
        freq.record = {
            "request_id": freq.spec.request_id, "state": "evicted",
            "finish_reason": "engine_failure",
            "prompt_tokens": len(freq.spec.tokens), "new_tokens": 0,
            "generated": [], "replica": None}
        freq.attempts.clear()
        # total fleet loss publishes no lifecycle event — the journey
        # root close below IS the tail-capture router's decision point
        self._close_journey(freq, t1=now, record=freq.record)

    def _shed_queued_for_drain(self, now: float) -> None:
        """The fleet-wide drain sweep (one per :meth:`begin_drain`):
        every request with no ADMITTED copy anywhere — still queued at
        its replica(s), or pending (re)dispatch — becomes a terminal
        retriable rejection; requests already in a slot finish in
        place. Queue waits were published by ``pop_queued``; the
        rejection itself rides ``serve_request_rejected`` like every
        other shed."""
        for freq in self._requests.values():
            if freq.record is not None:
                continue
            for rid, att in list(freq.attempts.items()):
                h = self._by_id[rid]
                if h.reachable and \
                        h.scheduler.pop_queued(att.request_id) is not None:
                    h.publish_progress()
                    del freq.attempts[rid]
                    self._end_attempt(freq, rid, t1=now,
                                      status="cancelled",
                                      reason="draining")
            if freq.attempts:
                continue    # admitted (or unreachable): finishes there
            freq.record = {
                "request_id": freq.spec.request_id, "state": "rejected",
                "finish_reason": "draining", "retriable": True,
                "prompt_tokens": len(freq.spec.tokens), "new_tokens": 0,
                "generated": [], "replica": None}
            publish_event("serve_request_rejected", level="warning",
                          request_id=freq.spec.request_id,
                          reason="draining", retriable=True,
                          seconds=0.0, queue_depth=0)
            self._close_journey(freq, t1=now, record=freq.record)
        self._pending = [f for f in self._pending if f.record is None]

    # ---------------------------------------------------------- hedging
    def _fire_hedges(self, now: float) -> None:
        if self.hedge_ms is None:
            return
        for freq in self._requests.values():
            if freq.record is not None or freq.hedged \
                    or len(freq.attempts) != 1 \
                    or freq.dispatch_t is None \
                    or now - freq.dispatch_t < self.hedge_ms / 1e3:
                continue
            primary = next(iter(freq.attempts))
            target = self._route(exclude=(primary,))
            if target is None:
                continue
            freq.hedged = True      # at most ONE hedge per request
            self.hedges_fired += 1
            waited_ms = round((now - freq.dispatch_t) * 1e3, 3)
            publish_event("serve_hedge_fired",
                          request_id=freq.spec.request_id,
                          primary=primary, hedge=target.replica_id,
                          waited_ms=waited_ms)
            if freq.spans is not None:
                # instant marker: the race opens here; the two attempt
                # spans racing after it ARE the hedge margin
                h = self.tracer.begin(
                    "hedge", parent=freq.spans["root"], t0=now,
                    primary=primary, hedge=target.replica_id,
                    waited_ms=waited_ms)
                self.tracer.end(h, t1=now)
            self._submit_attempt(freq, target, now)

    # --------------------------------------------- drain / rolling restart
    def drain(self, replica_id: str, *, wait: bool = True,
              max_wall_s: float = 30.0) -> int:
        """Mark a replica draining: no new admissions route to it, its
        still-queued requests migrate to peers (the scheduler's
        ``pop_queued`` hook — no terminal status, the fleet record stays
        exactly-once), in-flight requests finish in place. With
        ``wait=True`` pumps until the replica is idle, then publishes
        ``serve_replica_drained``. Returns the migration count."""
        handle = self._by_id[str(replica_id)]
        self.registry.set_state(handle.replica_id, REPLICA_DRAINING)
        now = self._clock()
        migrated = 0
        for freq in self._requests.values():
            att = freq.attempts.get(handle.replica_id)
            if att is None or freq.record is not None:
                continue
            popped = handle.scheduler.pop_queued(att.request_id)
            if popped is None:
                continue    # already in a slot: finishes where it is
            handle.publish_progress()
            del freq.attempts[handle.replica_id]
            migrated += 1
            self.migrations += 1
            lost_t0 = freq.attempt_t.get(handle.replica_id, now)
            seconds = round(max(now - lost_t0, 0.0), 6)
            self._end_attempt(freq, handle.replica_id, t1=now,
                              status="cancelled", cause="drain",
                              seconds=seconds)
            target = self._route(exclude=(handle.replica_id,))
            publish_event(
                "serve_failover", request_id=freq.spec.request_id,
                from_replica=handle.replica_id,
                to_replica=target.replica_id if target else None,
                cause="drain", seconds=seconds)
            if freq.spans is not None:
                # same contract as the death path: span window == the
                # migrated wait, seconds attr == the event's value
                fo = self.tracer.begin(
                    "failover", parent=freq.spans["root"], t0=lost_t0,
                    from_replica=handle.replica_id,
                    to_replica=target.replica_id if target else None,
                    cause="drain", seconds=seconds)
                self.tracer.end(fo, t1=now)
            if target is not None:
                self._submit_attempt(freq, target, now)
            else:
                freq.next_dispatch_t = now
                self._pending.append(freq)
        self._drain_migrated[handle.replica_id] = migrated
        if wait:
            t0 = self._clock()
            while self.registry.state(handle.replica_id) \
                    == REPLICA_DRAINING:
                self.pump()     # pump marks it drained at load 0
                if self._clock() - t0 > max_wall_s:
                    raise TimeoutError(
                        f"replica {replica_id} did not drain within "
                        f"{max_wall_s}s (load={handle.load()})")
                time.sleep(self._pump_interval_s)
        else:
            # already idle? mark now — otherwise every later pump()
            # checks, so wait=False can never wedge it in draining
            self._maybe_mark_drained(handle)
        return migrated

    def _maybe_mark_drained(self, handle: EngineReplica) -> None:
        """Draining → drained the moment the replica is idle (exactly
        one ``serve_replica_drained`` per drain — the state transition
        is the guard). A draining PREFILL replica must first flush its
        committed-but-undelivered page handoffs (``pending_handoffs``)
        — declaring it drained with pages in flight would strand KV
        state its decode targets are counting on; the disaggregation
        controller's pump delivers them and drops the count to zero."""
        if self.registry.state(handle.replica_id) == REPLICA_DRAINING \
                and handle.load() == 0 and handle.pending_handoffs == 0:
            self.registry.set_state(handle.replica_id, REPLICA_DRAINED)
            publish_event(
                "serve_replica_drained", replica=handle.replica_id,
                migrated=self._drain_migrated.get(handle.replica_id, 0))

    def restart_replica(self, replica_id: str) -> None:
        """Clean restart of a drained (or dead) replica: engine state
        reset with every compiled artifact kept — zero recompiles — and
        the registry re-admits it (``serve_replica_restarted``). The
        ONLY way back in for a dead replica: a healed partition's
        heartbeats alone never re-admit it."""
        handle = self._by_id[str(replica_id)]
        state = self.registry.state(handle.replica_id)
        if state not in (REPLICA_DRAINED, REPLICA_DEAD):
            raise ValueError(
                f"replica {replica_id!r} is {state!r}: drain it (or let "
                f"the sweep declare it dead) before restarting")
        if self._started:
            handle.restart()
        else:
            # not running yet (pre-start lifecycle tests): reset only
            if handle.scheduler.load():
                handle.scheduler.drain_and_reject("engine_failure")
            handle.engine.reset()
            handle.crashed = False
            handle.partitioned = False
            handle.publish_progress()
        self.registry.set_state(handle.replica_id, REPLICA_HEALTHY,
                                beat=True)
        self.replica_restarts += 1
        publish_event("serve_replica_restarted",
                      replica=handle.replica_id)

    def add_replica(self, handle: EngineReplica) -> None:
        """Admit a freshly-built replica into a running fleet (the
        autoscaler's cold-spawn path — warm restarts of a DRAINED
        standby go through :meth:`restart_replica` instead and cost
        zero recompiles). The handle registers healthy with a fresh
        heartbeat stamp and, if the fleet is started, its worker starts
        immediately; ``serve_replica_spawned`` records the spawn."""
        rid = handle.replica_id
        if rid in self._by_id:
            raise ValueError(
                f"replica id {rid!r} already in the fleet (spawn needs "
                f"a unique id; restart the existing one instead)")
        handle.index = len(self.handles)
        self.handles.append(handle)
        self._by_id[rid] = handle
        self.registry.register(rid)
        if self._started:
            handle.start(self.registry, self.injector)
        publish_event("serve_replica_spawned", replica=rid,
                      role=handle.role, replicas=len(self.handles))

    def rolling_restart(self, *, max_wall_s: float = 30.0
                        ) -> Dict[str, int]:
        """Drain → restart every non-dead replica, one at a time, so
        admitting capacity never drops below N-1 (the returned
        ``min_admitting`` proves it — tier-1 asserts) and zero in-flight
        requests are lost (queued ones migrate, running ones finish)."""
        self._min_admitting = len(self.handles)
        restarted = 0
        for handle in self.handles:
            if self.registry.state(handle.replica_id) == REPLICA_DEAD:
                continue
            self.drain(handle.replica_id, wait=True,
                       max_wall_s=max_wall_s)
            self.restart_replica(handle.replica_id)
            restarted += 1
        return {"restarted": restarted,
                "min_admitting": self._min_admitting}

    # ------------------------------------------------------------- stats
    def stats(self) -> FleetStats:
        records = [dict(f.record) for f in self._requests.values()
                   if f.record is not None]
        # attempt-level counters, classified exactly the way the
        # per-replica ServeMetrics hooks count them (state rejected →
        # on_reject, deadline eviction → on_deadline, other evictions →
        # on_evict, completed → on_complete) — so the merged snapshot's
        # family totals must equal these, counter for counter
        attempts = {"submitted": self.dispatches, "completed": 0,
                    "evicted": 0, "deadline_exceeded": 0, "rejected": 0}
        pooled_steps: List[float] = []
        per_replica: Dict[str, Dict[str, Any]] = {}
        for h in self.handles:
            done, _ = h.scheduler.done_since(0)
            for r in done:
                if r.state == "completed":
                    attempts["completed"] += 1
                elif r.state == "rejected":
                    attempts["rejected"] += 1
                elif r.finish_reason == "deadline":
                    attempts["deadline_exceeded"] += 1
                else:
                    attempts["evicted"] += 1
            pooled_steps.extend(h.scheduler.decode_step_s)
            per_replica[h.replica_id] = {
                "state": self.registry.state(h.replica_id),
                "decode_steps": h.scheduler.decode_steps,
                "attempts_done": len(done),
                "crashed": h.crashed,
            }
        wall = (self._clock() - self._t0) if self._t0 is not None else 0.0
        return FleetStats(
            requests=records, replicas=len(self.handles),
            failovers=self.failovers, hedge_fired=self.hedges_fired,
            migrations=self.migrations, retries=self.retries,
            replica_dead=self.replica_deaths,
            replica_restarted=self.replica_restarts,
            attempts=attempts, per_replica=per_replica,
            decode_step_s=pooled_steps, wall_s=wall)


# --------------------------------------------------------------------------
# --trace-jsonl fleet wiring (shared by apex-tpu-serve and apex-tpu-bench)
# --------------------------------------------------------------------------

class FleetTraceHarness:
    """One object owning the whole fleet tracing surface: a fleet-plane
    :class:`~apex_tpu.monitor.trace.Tracer` (track ``fleet``) streaming
    to ``PATH``, one tracer per replica (track ``rK``) streaming to
    ``PATH.rK``, and a :class:`~apex_tpu.monitor.trace.TailCaptureRouter`
    applying the seeded head-sampling + tail-capture policy across all of
    them (``sample_rate=1`` — the default — streams every journey, the
    pre-PR-13 behavior).

    Usage::

        harness = FleetTraceHarness(path, [h.replica_id for h in handles],
                                    sample_rate=0.1, sample_seed=seed)
        fleet = FleetController(handles, tracer=harness.fleet_tracer, ...)
        # EngineReplica(..., tracer=harness.tracer_for(rid)) per replica
        try:
            fleet.run()
        finally:
            harness.close()    # finalize every trace file

    ``tools/trace_explain.py PATH PATH.r0 ...`` merges the files back
    into per-request attribution and verifies the reconciliation.
    """

    def __init__(self, path: str, replica_ids: Sequence[str], *,
                 sample_rate: float = 1.0, sample_seed: int = 0,
                 ring_spans: int = 256):
        self.path = path
        self.fleet_tracer = Tracer(tags={"track": "fleet"})
        self.replica_tracers = {
            str(rid): Tracer(tags={"track": str(rid)})
            for rid in replica_ids}
        # dict order matters: the fleet writer is first, so untracked
        # spans (none in practice) default to the fleet file
        writers = {"fleet": ChromeTraceWriter(path, subscribe=False)}
        for rid in self.replica_tracers:
            writers[rid] = ChromeTraceWriter(f"{path}.{rid}",
                                             subscribe=False)
        self.router = TailCaptureRouter(writers, sample_rate=sample_rate,
                                        sample_seed=sample_seed,
                                        ring_spans=ring_spans)

    def tracer_for(self, replica_id: str):
        return self.replica_tracers[str(replica_id)]

    @property
    def paths(self) -> List[str]:
        return [self.path] + [f"{self.path}.{rid}"
                              for rid in self.replica_tracers]

    def stats(self) -> Dict[str, Any]:
        """Sampling/promotion provenance for the CLI summary and the
        bench entry (``trace_promoted`` gates lower-is-better)."""
        return {"sample_rate": self.router.sampler.rate,
                "sample_seed": self.router.sampler.seed,
                **self.router.stats()}

    def close(self) -> None:
        self.router.close()

    def __enter__(self) -> "FleetTraceHarness":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def attach_fleet_recorders(fleet: FleetController, path: str,
                           harness: Optional[FleetTraceHarness] = None
                           ) -> List[FlightRecorder]:
    """``--flight-recorder`` fleet wiring, shared by ``apex-tpu-serve``
    and ``apex-tpu-bench --serve`` (one spelling — the two CLIs'
    postmortems can never diverge): one recorder per replica at
    ``PATH.rK``, auto-dump scoped (``trigger_filter``) to THAT replica's
    death/suspect transition and carrying its registry row
    (``context_fn``) plus its tracer's open spans; plus the fleet-plane
    recorder at ``PATH``, returned LAST — wrap the control loop in its
    ``guard()`` (a fatal controller error has no bus record to trigger
    on). The caller detaches every returned recorder in its teardown."""
    recorders: List[FlightRecorder] = []
    for h in fleet.handles:
        rid = h.replica_id
        recorders.append(FlightRecorder(
            f"{path}.{rid}",
            tracer=harness.tracer_for(rid) if harness is not None
            else None,
            trigger_filter=lambda rec, rid=rid:
            rec.get("replica") in (None, rid),
            context_fn=lambda rid=rid:
            fleet.registry.row(rid)).attach())
    recorders.append(FlightRecorder(
        path,
        tracer=harness.fleet_tracer if harness is not None
        else None).attach())
    return recorders
