"""Decode attention over the static KV cache — slot-contiguous or paged.

One query token per slot against that slot's cached keys/values. The key
axis is static (the slot cache's ``max_len``, or the paged cache's
``max_pages_per_slot * page_size`` virtual axis); reachability is a mask
(``key_pos <= position``), never a shape — so the op compiles once and a
slot's result depends only on that slot's bytes (reductions run within a
slot; other slots' values cannot perturb the arithmetic, which is what
makes mid-stream eviction bit-invisible to its neighbors).

The softmax is computed in explicitly chunked form over the key axis:
``block_k`` cached rows per partial reduction, partials combined in a
static python loop. The chunk geometry is what :mod:`apex_tpu.tune` tunes
(kernel name ``decode_attention``): on TPU the XLA fusion streams one
``[block_k, head_dim]`` K/V tile at a time through VMEM, so the block size
is a real tile-geometry knob, with
:func:`~apex_tpu.ops.pallas.tiling.decode_attention_block` as the
committed heuristic. Both the prefill scan body and the decode step call
this function with the same geometry, so the two paths stay bit-identical.

**The paged path shares the slot path's arithmetic verbatim**: the only
difference is where a chunk's K/V rows are fetched from (a contiguous
slice of the slot's buffer vs. a page-table gather — ``block_k`` divides
``page_size``, so every chunk lives inside exactly one page). Scores,
masking, the max combine, and the sum order are the same code, which is
why a paged engine is bit-exact in fp32 against the slot engine on
identical traces **at the same block_k** (tier-1 asserts, with the slot
cache as the oracle). The *default* chunk differs per layout — the
heuristic/tuner unit is ``max_len`` for the slot cache but ``page_size``
for the pool — and a different ``block_k`` reorders the partial sums by
design (±1 ulp), exactly as it does between two ``block_k`` values on
the same layout; pin ``block_k`` to compare layouts bitwise.

All math fp32 (max-subtracted softmax; the row's own token is always
reachable, so the denominator is never empty); IO dtype preserved.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas.tiling import decode_attention_block
from apex_tpu.tune.api import tuned_params

_f32 = jnp.float32
NEG_INF = jnp.float32(-1e30)


def resolve_block_k(max_len: int, heads: int, head_dim: int, dtype,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    page_size: Optional[int] = None,
                    tp_shards: int = 1) -> int:
    """The decode KV-chunk size: explicit value (validated), else the
    autotuned winner for this (max_len, page_size, heads, head_dim,
    tp_shards, dtype, chip), else the committed heuristic.

    With a paged cache (``page_size`` set) the chunk must additionally
    divide ``page_size`` so every chunk's rows live inside one page —
    the fetch is then a single page gather plus a static slice, and the
    geometry the autotuner times is the true streamed working set.

    ``tp_shards`` is the tensor-parallel mesh size the attention runs
    under (1 = single chip): a sharded engine passes its PER-SHARD head
    count as ``heads``, and the shard count is its own exact key axis —
    the per-shard working set that the tuner times is a different
    kernel instance than an unsharded engine with the same local head
    count (collective pressure and VMEM headroom differ), so winners
    never leak across mesh shapes.
    """
    if page_size is not None:
        ps = int(page_size)
        if ps <= 0 or max_len % ps:
            raise ValueError(
                f"page_size={ps} must be positive and divide the cache "
                f"max_len={max_len}")
    if block_k is not None:
        bk = int(block_k)
        if bk <= 0 or max_len % bk:
            raise ValueError(
                f"block_k={bk} must be positive and divide the cache "
                f"max_len={max_len} (the chunked softmax tiles the static "
                f"key axis exactly)")
        if page_size is not None and int(page_size) % bk:
            raise ValueError(
                f"block_k={bk} must divide page_size={page_size}: each "
                f"chunked-softmax tile must live inside one KV page "
                f"(pick a block_k that divides the page, or a page_size "
                f"that is a multiple of the tuned block)")
        return bk
    # max_len is keyed EXACTLY (not pow2-bucketed): it is a static,
    # layout-defining engine constant and the winner must divide it — a
    # bucketed key would warm entries that can never validate for
    # non-pow2 cache lengths. page_size is a geometry axis of the same
    # kind (0 = slot cache): a winner tuned for one page size cannot
    # apply to another.
    ps = int(page_size) if page_size is not None else 0
    unit = ps if ps else int(max_len)
    p = tuned_params(
        "decode_attention",
        (("max_len", int(max_len)), ("page_size", ps), ("heads", heads),
         ("d", head_dim), ("tp_shards", int(tp_shards))),
        {"block_k": decode_attention_block(unit)},
        dtype=dtype, interpret=interpret,
        validate=lambda pr: (pr["block_k"] > 0
                             and max_len % pr["block_k"] == 0
                             and (not ps or ps % pr["block_k"] == 0)))
    return int(p["block_k"])


def _combine_chunks(q: jax.Array, positions: jax.Array, L: int, bk: int,
                    scale: jnp.float32,
                    fetch: Callable[[int], Tuple[jax.Array, jax.Array]],
                    ) -> jax.Array:
    """The shared chunked-softmax core: ``fetch(i)`` returns chunk ``i``'s
    ``(k_rows, v_rows)`` as ``[b, block_k, heads, head_dim]`` — a
    contiguous slice for the slot cache, a page gather for the paged pool.
    Everything numeric happens HERE, identically for both layouts: each
    score's reduction runs over ``d`` (not ``L``), the global row max
    equals the max over chunk maxima bit-for-bit, and only the SUM order
    depends on ``block_k`` — identically in prefill and decode, and
    identically in slot and paged engines.
    """
    b, h, d = q.shape
    q32 = q.astype(_f32)
    pos = positions.astype(jnp.int32)[:, None, None]
    nchunk = L // bk

    def chunk_scores(i):
        ks, vs = fetch(i)                 # ONE fetch per chunk: a second
        # call would trace the K and V gathers twice (and execute them
        # twice under interpret=True) just to rely on XLA CSE
        sc = jnp.einsum("bhd,bkhd->bhk", q32, ks.astype(_f32)) * scale
        kpos = jnp.arange(i * bk, (i + 1) * bk, dtype=jnp.int32)
        reach = kpos[None, None, :] <= pos
        return jnp.where(reach, sc, NEG_INF), reach, vs

    chunks = [chunk_scores(i) for i in range(nchunk)]      # static unroll
    m = chunks[0][0].max(axis=-1, keepdims=True)
    for sc, _, _ in chunks[1:]:
        m = jnp.maximum(m, sc.max(axis=-1, keepdims=True))

    num = jnp.zeros((b, h, d), _f32)
    den = jnp.zeros((b, h), _f32)
    for sc, reach, vs in chunks:
        e = jnp.where(reach, jnp.exp(sc - m), 0.0)         # [b, h, bk]
        den = den + jnp.sum(e, axis=-1)
        num = num + jnp.einsum("bhk,bkhd->bhd", e, vs.astype(_f32))
    return (num / den[..., None]).astype(q.dtype)


def cached_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     positions: jax.Array, *,
                     scale: Optional[float] = None,
                     block_k: Optional[int] = None,
                     interpret: Optional[bool] = None,
                     k_scale: Optional[jax.Array] = None,
                     v_scale: Optional[jax.Array] = None) -> jax.Array:
    """Single-token attention over slot-contiguous cached K/V.

    ``q``: ``[num_slots, heads, head_dim]`` (this step's query per slot);
    ``k_cache``/``v_cache``: ``[num_slots, max_len, heads, head_dim]``;
    ``positions``: ``[num_slots]`` int32 — slot ``b`` attends to cached
    positions ``0 .. positions[b]`` inclusive (its own just-appended token
    is position ``positions[b]``). Returns ``[num_slots, heads, head_dim]``
    in ``q.dtype``.

    ``k_scale``/``v_scale`` (``[num_slots, max_len, heads]`` fp32, from a
    ``kv_quant`` cache) arm per-(token, head) dequantization INSIDE the
    chunk fetch: each streamed ``[block_k]`` tile is decoded to fp32 as
    it is read, so the scores/combine arithmetic below never changes and
    the dequant working set is bounded by the same ``block_k`` tile.
    """
    b, L, h, d = k_cache.shape
    bk = resolve_block_k(L, h, d, q.dtype, block_k, interpret)
    s = jnp.float32(scale if scale is not None else 1.0 / (d ** 0.5))

    # fully chunked over the key axis: scores, masking, exp, and the
    # V-side accumulation all touch one [block_k] tile of K and V per
    # step, so block_k genuinely bounds the streamed working set (the
    # premise the decode_attention autotuner times)
    def fetch(i):
        sl = slice(i * bk, (i + 1) * bk)
        ks, vs = k_cache[:, sl], v_cache[:, sl]
        if k_scale is not None:
            ks = ks.astype(_f32) * k_scale[:, sl][..., None]
            vs = vs.astype(_f32) * v_scale[:, sl][..., None]
        return ks, vs

    return _combine_chunks(q, positions, L, bk, s, fetch)


def paged_attention(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                    page_table: jax.Array, positions: jax.Array, *,
                    scale: Optional[float] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    k_scale: Optional[jax.Array] = None,
                    v_scale: Optional[jax.Array] = None) -> jax.Array:
    """Single-token attention through the page table.

    ``q``: ``[num_slots, heads, head_dim]``; ``k_pool``/``v_pool``:
    ``[num_pages, page_size, heads, head_dim]`` (one layer of the paged
    pool); ``page_table``: ``[num_slots, max_pages_per_slot]`` int32;
    ``positions``: ``[num_slots]`` int32 over each slot's VIRTUAL key
    axis (page-table row laid flat). Chunk ``i`` of the virtual axis
    lives inside page ``page_table[:, (i * block_k) // page_size]``
    (``block_k`` divides ``page_size``), so the fetch is one page gather
    plus a static in-page slice — the working set per partial reduction
    is the same ``[block_k, head_dim]`` tile as the slot path, and the
    combine is the SAME code, bit-for-bit. Unmapped table entries point
    at the null page; its rows sit past every live position, so the
    reachability mask discards them.

    ``k_scale``/``v_scale`` (``[num_pages, page_size, heads]`` fp32, one
    layer of a ``kv_quant`` pool's scale planes) dequantize each fetched
    tile through the SAME page gather as the payload — the scales ride
    the page table, so sharing/COW/eviction need no quant-aware code.
    """
    P, ps, h, d = k_pool.shape
    L = int(page_table.shape[1]) * ps
    bk = resolve_block_k(L, h, d, q.dtype, block_k, interpret,
                         page_size=ps)
    s = jnp.float32(scale if scale is not None else 1.0 / (d ** 0.5))

    def fetch(i):
        start = i * bk
        pages = page_table[:, start // ps]                 # [b]
        sl = slice(start % ps, start % ps + bk)            # static in-page
        ks, vs = k_pool[pages, sl], v_pool[pages, sl]
        if k_scale is not None:
            ks = ks.astype(_f32) * k_scale[pages, sl][..., None]
            vs = vs.astype(_f32) * v_scale[pages, sl][..., None]
        return ks, vs

    return _combine_chunks(q, positions, L, bk, s, fetch)
