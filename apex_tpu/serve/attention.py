"""Decode attention over the static KV cache.

One query token per slot against that slot's cached keys/values. The key
axis is the cache's static ``max_len``; reachability is a mask
(``key_pos <= position``), never a shape — so the op compiles once and a
slot's result depends only on that slot's bytes (reductions run within a
slot; other slots' values cannot perturb the arithmetic, which is what
makes mid-stream eviction bit-invisible to its neighbors).

The softmax is computed in explicitly chunked form over the key axis:
``block_k`` cached rows per partial reduction, partials combined in a
static python loop. The chunk geometry is what :mod:`apex_tpu.tune` tunes
(kernel name ``decode_attention``): on TPU the XLA fusion streams one
``[block_k, head_dim]`` K/V tile at a time through VMEM, so the block size
is a real tile-geometry knob, with
:func:`~apex_tpu.ops.pallas.tiling.decode_attention_block` as the
committed heuristic. Both the prefill scan body and the decode step call
this function with the same geometry, so the two paths stay bit-identical.

All math fp32 (max-subtracted softmax; the row's own token is always
reachable, so the denominator is never empty); IO dtype preserved.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.pallas.tiling import decode_attention_block
from apex_tpu.tune.api import tuned_params

_f32 = jnp.float32
NEG_INF = jnp.float32(-1e30)


def resolve_block_k(max_len: int, heads: int, head_dim: int, dtype,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> int:
    """The decode KV-chunk size: explicit value (validated), else the
    autotuned winner for this (max_len, heads, head_dim, dtype, chip),
    else the committed heuristic."""
    if block_k is not None:
        bk = int(block_k)
        if bk <= 0 or max_len % bk:
            raise ValueError(
                f"block_k={bk} must be positive and divide the cache "
                f"max_len={max_len} (the chunked softmax tiles the static "
                f"key axis exactly)")
        return bk
    # max_len is keyed EXACTLY (not pow2-bucketed): it is a static,
    # layout-defining engine constant and the winner must divide it — a
    # bucketed key would warm entries that can never validate for
    # non-pow2 cache lengths
    p = tuned_params(
        "decode_attention",
        (("max_len", int(max_len)), ("heads", heads), ("d", head_dim)),
        {"block_k": decode_attention_block(max_len)},
        dtype=dtype, interpret=interpret,
        validate=lambda pr: (pr["block_k"] > 0
                             and max_len % pr["block_k"] == 0))
    return int(p["block_k"])


def cached_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     positions: jax.Array, *,
                     scale: Optional[float] = None,
                     block_k: Optional[int] = None,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Single-token attention over cached K/V.

    ``q``: ``[num_slots, heads, head_dim]`` (this step's query per slot);
    ``k_cache``/``v_cache``: ``[num_slots, max_len, heads, head_dim]``;
    ``positions``: ``[num_slots]`` int32 — slot ``b`` attends to cached
    positions ``0 .. positions[b]`` inclusive (its own just-appended token
    is position ``positions[b]``). Returns ``[num_slots, heads, head_dim]``
    in ``q.dtype``.
    """
    b, L, h, d = k_cache.shape
    bk = resolve_block_k(L, h, d, q.dtype, block_k, interpret)
    s = jnp.float32(scale if scale is not None else 1.0 / (d ** 0.5))

    # fully chunked over the key axis: scores, masking, exp, and the
    # V-side accumulation all touch one [block_k] tile of K and V per
    # step, so block_k genuinely bounds the streamed working set (the
    # premise the decode_attention autotuner times). Chunking changes no
    # value: each score's reduction runs over d (not L), and the global
    # row max equals the max over chunk maxima bit-for-bit — only the
    # SUM order depends on block_k, identically in prefill and decode.
    q32 = q.astype(_f32)
    pos = positions.astype(jnp.int32)[:, None, None]
    nchunk = L // bk

    def chunk_scores(i):
        ks = k_cache[:, i * bk:(i + 1) * bk].astype(_f32)
        sc = jnp.einsum("bhd,bkhd->bhk", q32, ks) * s     # [b, h, bk]
        kpos = jnp.arange(i * bk, (i + 1) * bk, dtype=jnp.int32)
        reach = kpos[None, None, :] <= pos
        return jnp.where(reach, sc, NEG_INF), reach

    chunks = [chunk_scores(i) for i in range(nchunk)]     # static unroll
    m = chunks[0][0].max(axis=-1, keepdims=True)
    for sc, _ in chunks[1:]:
        m = jnp.maximum(m, sc.max(axis=-1, keepdims=True))

    num = jnp.zeros((b, h, d), _f32)
    den = jnp.zeros((b, h), _f32)
    for i, (sc, reach) in enumerate(chunks):
        e = jnp.where(reach, jnp.exp(sc - m), 0.0)        # [b, h, bk]
        den = den + jnp.sum(e, axis=-1)
        num = num + jnp.einsum(
            "bhk,bkhd->bhd", e, v_cache[:, i * bk:(i + 1) * bk]
            .astype(_f32))
    return (num / den[..., None]).astype(q.dtype)
