"""Host-side page accounting for the paged KV pool — free-list
allocator, refcounts, and the hash-based prefix index.

Everything in this module is pure host python: page *indices* are data
the engine threads into its compiled calls (a page table is values, never
shapes), so allocation policy lives out here where it can be unit-tested
without a device. The device-side pool itself is
:class:`~apex_tpu.serve.kv_cache.PagedKVCache`.

Invariants the engine relies on:

- **page 0 is the null page** — never allocated, never written with live
  data. Masked-off slots' decode write-backs are routed to it so a stale
  page-table entry can never collide with a live slot's append in the
  same scatter, and unmapped table entries read zeros that the
  reachability mask discards.
- **refcount = number of slot page-table references + 1 if the page is
  held by the prefix index.** A page returns to the free list exactly
  when its refcount reaches zero; shared prefix pages therefore survive
  the requests that created them until LRU pressure evicts the index
  entry.
- **shared pages are read-only.** Appends only ever target pages a
  single slot owns: prefill writes start at the first non-shared
  position (the partial tail page is copied — copy-on-write — before it
  is written), and decode appends land past the prompt. Nothing enforces
  this on-device; the allocator's job is to make it structurally true.
- **page indices are rank-invariant.** Under tensor parallelism the
  pool's bytes shard on the HEAD axis (each mesh rank holds every
  page's slice of its own heads), so one page index addresses all
  ranks' shards of that page simultaneously — this ONE allocator, the
  prefix index, and copy-on-write serve the whole mesh unchanged, and
  the page table rides into the sharded step as replicated data.
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

NULL_PAGE = 0


class PagePoolExhausted(RuntimeError):
    """No free page available (after prefix-index LRU eviction). The
    scheduler treats this as an admission stall, not an error: the
    request stays queued and ``serve_page_alloc_fail`` charges the
    waiting time once pages free up."""


def chunk_hashes(tokens: Sequence[int], page_size: int) -> List[str]:
    """Chained content hashes of the full ``page_size``-token chunks of
    ``tokens`` — hash ``i`` commits to chunks ``0..i``, so an index hit
    on hash ``i`` certifies the *entire* prefix up to ``(i+1) *
    page_size`` tokens, not just one chunk. Stable across processes
    (blake2b over the token bytes, never python ``hash``)."""
    import numpy as np

    out: List[str] = []
    h = b""
    for i in range(len(tokens) // page_size):
        chunk = np.asarray(tokens[i * page_size:(i + 1) * page_size],
                           np.int64).tobytes()
        h = hashlib.blake2b(h + chunk, digest_size=16).digest()
        out.append(h.hex())
    return out


def page_payload_digest(chain_hash: str, k_bytes: bytes,
                        v_bytes: bytes, *extra: bytes) -> str:
    """Transport digest for one migrated KV page: blake2b over the chain
    hash it claims plus the raw K/V bytes. The sender stamps it at
    export; the receiver recomputes it over what actually arrived, so a
    bit flip or torn copy in flight fails certification even though the
    *claimed* chain hash still matches the receiver's expectation. Two
    independent checks, two failure classes: the chain hash certifies
    "these are the pages for THIS prompt prefix", the payload digest
    certifies "these bytes are the ones the prefill replica committed".

    ``extra`` carries any further byte planes the page's meaning depends
    on — a quantized page passes its K/V scale planes here, so the
    digest certifies codes ‖ scales TOGETHER: a flipped bit in a scale
    (which would silently rescale a whole (token, head) block at
    dequant) is refused exactly like a flipped payload bit."""
    h = hashlib.blake2b(digest_size=16)
    h.update(bytes.fromhex(chain_hash))
    h.update(k_bytes)
    h.update(v_bytes)
    for b in extra:
        h.update(b)
    return h.hexdigest()


class PagePool:
    """Free-list page allocator with refcounts over ``num_pages`` device
    pages (page 0 reserved as the null page)."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages={num_pages} must be >= 2 (page 0 is the "
                f"reserved null page)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # ascending allocation order (deterministic: identical request
        # traces produce identical page tables, which tests rely on)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self.refcount: List[int] = [0] * num_pages

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def capacity(self) -> int:
        """Allocatable pages (the null page is never allocatable)."""
        return self.num_pages - 1

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` fresh pages (refcount 1 each). Raises
        :class:`PagePoolExhausted` without allocating anything when the
        free list is short — the caller probes first, so this firing
        means a bookkeeping bug, not load."""
        if n > len(self._free):
            raise PagePoolExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"(of {self.capacity})")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self.refcount[p] = 1
        return pages

    def retain(self, page: int) -> None:
        """Add a reference to an already-live page (a slot sharing a
        prefix page, or the prefix index pinning one)."""
        if page == NULL_PAGE or self.refcount[page] <= 0:
            raise ValueError(f"retain of dead page {page}")
        self.refcount[page] += 1

    def release(self, page: int) -> bool:
        """Drop one reference; returns True when the page went back to
        the free list."""
        if page == NULL_PAGE:
            raise ValueError("release of the null page")
        if self.refcount[page] <= 0:
            raise ValueError(f"release of dead page {page}")
        self.refcount[page] -= 1
        if self.refcount[page] == 0:
            self._free.append(page)
            return True
        return False


class PrefixIndex:
    """Page-granular prompt-prefix index: chained chunk hash → resident
    read-only page, LRU-ordered.

    Pages inserted here carry one index reference in the
    :class:`PagePool`, so they outlive the request that prefilled them;
    :meth:`evict` drops least-recently-used entries (index-only pages go
    straight back to the free list) when allocation needs room.
    """

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        # chain hash -> page index, in LRU order (oldest first)
        self._entries: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, chain_hash: str) -> bool:
        return chain_hash in self._entries

    def pages(self) -> Set[int]:
        return set(self._entries.values())

    def lookup(self, tokens: Sequence[int], *,
               touch: bool = True) -> List[Tuple[str, int]]:
        """The longest indexed prefix of ``tokens``: ``[(chain_hash,
        page), ...]`` for consecutive full chunks from position 0. With
        ``touch`` (the default) hit entries are refreshed in LRU order;
        admission *probes* pass ``touch=False`` so a rejected probe does
        not reorder the index."""
        out: List[Tuple[str, int]] = []
        for h in chunk_hashes(tokens, self.page_size):
            page = self._entries.get(h)
            if page is None:
                break
            out.append((h, page))
        if touch:
            for h, _ in out:
                self._entries.move_to_end(h)
            self.hits += len(out)
            if len(tokens) // self.page_size > len(out):
                self.misses += 1
        return out

    def insert(self, chain_hash: str, page: int, pool: PagePool) -> None:
        """Pin ``page`` (already live — the inserting slot references
        it) under ``chain_hash``; no-op when the hash is already
        indexed."""
        if chain_hash in self._entries:
            return
        pool.retain(page)
        self._entries[chain_hash] = page

    def evict(self, pool: PagePool, need: int,
              protect: Iterable[int] = ()) -> int:
        """Drop LRU entries until ``need`` pages have returned to the
        free list. Entries whose page a live slot still references are
        skipped — dropping them frees nothing and loses a prefix some
        request is actively using. ``protect`` names pages an in-progress
        admission is about to share — evicting those would free pages the
        caller is counting on reusing."""
        protected = set(protect)
        freed = 0
        for h in list(self._entries):
            if freed >= need:
                break
            page = self._entries[h]
            if page in protected or pool.refcount[page] > 1:
                continue
            del self._entries[h]
            self.evictions += 1
            if pool.release(page):
                freed += 1
        return freed

    def evictable(self, pool: PagePool,
                  protect: Iterable[int] = ()) -> int:
        """How many pages an :meth:`evict` sweep could free right now:
        index entries not protected whose only reference is the index
        itself."""
        protected = set(protect)
        return sum(1 for page in self._entries.values()
                   if page not in protected and pool.refcount[page] == 1)

    def drop_page(self, page: int, pool: PagePool) -> None:
        """Remove every entry pointing at ``page`` (used when a caller
        must reclaim a specific page, e.g. tests)."""
        for h, p in list(self._entries.items()):
            if p == page:
                del self._entries[h]
                self.evictions += 1
                pool.release(page)


def pages_for_tokens(n_tokens: int, page_size: int) -> int:
    """Pages needed to hold ``n_tokens`` KV rows."""
    return -(-int(n_tokens) // int(page_size))


def plan_admission(tokens: Sequence[int], budget: int, max_len: int,
                   page_size: int,
                   index: Optional[PrefixIndex], *,
                   touch: bool = False) -> Dict[str, object]:
    """The page plan for admitting ``tokens`` with ``budget`` new-token
    headroom: which prefix pages to share, whether the partial tail page
    is copy-on-write, and how many fresh pages to allocate. Pure
    function of the index state — both the admission *probe* (``touch``
    False) and the actual allocation (``touch`` True) use it, so they
    can never disagree about the page count.

    Note ``use = min(shared, len(tokens) - 1)``: at least the final
    prompt token is always re-run through prefill, because its logits
    seed the first sampled token — a fully-cached prompt caps its hit
    one token short, which is what makes the partial-tail COW case.
    """
    n = len(tokens)
    hits = index.lookup(tokens, touch=touch) if index is not None else []
    # clamp at 0: an empty prompt (n=0, legal on the slot path) must plan
    # zero shared tokens, not use=-1 (whose tail-page remainder would
    # index hits[-1] on an empty hit list)
    use = max(0, min(len(hits) * page_size, n - 1))
    shared_pages = use // page_size
    cow_src = hits[shared_pages][1] if use % page_size else None
    total_tokens = min(n + max(int(budget), 1), max_len)
    total_pages = pages_for_tokens(total_tokens, page_size)
    new_pages = total_pages - shared_pages
    return {
        "hits": hits[:shared_pages + (1 if cow_src is not None else 0)],
        "use": use,                      # tokens served from the index
        "shared_pages": shared_pages,    # full read-only pages shared
        "cow_src": cow_src,              # page to copy for the tail, or None
        "total_pages": total_pages,      # final page-table row length
        "new_pages": new_pages,          # fresh allocations (incl. the COW)
        "tail": list(tokens[use:]),      # tokens prefill actually scans
    }
