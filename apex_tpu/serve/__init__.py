"""TPU-native inference engine — static-shape KV cache, one-jit decode,
continuous batching.

Serving throughput on TPU is won by keeping the compiled graph stable
(TokenWeave, arXiv:2505.11329; operation-fusion serving, arXiv:2502.17728):
XLA rewards a single jitted decode step over fixed-shape buffers, and
punishes anything that changes shapes mid-stream with a recompile that
costs more than the tokens it produces. This package is built around that
one invariant:

- :mod:`~apex_tpu.serve.kv_cache` — a slot-addressed, static-shape KV
  cache pytree (``[n_layer, num_slots, max_len, heads, head_dim]`` plus a
  per-slot length vector). ``insert``/``append``/``evict`` are pure,
  jittable, mask-driven ops: batch membership changes (a request finishes,
  another backfills its slot) never change a shape and therefore never
  trigger a recompile.
- :mod:`~apex_tpu.serve.engine` — AOT-lowered ``prefill`` and the ONE
  jitted ``decode_step``: every token in the system, prefill or decode,
  flows through the same ``[num_slots, 1]`` forward, so incremental decode
  is bit-identical to prefill in fp32 and slots are arithmetically
  isolated from each other.
- :mod:`~apex_tpu.serve.scheduler` — continuous batching: an admission
  queue, slot assignment, per-request EOS/max-token termination, eviction
  and backfill between decode steps, with TTFT/latency/throughput
  accounting and ``serve_*`` events on the telemetry bus.
- :mod:`~apex_tpu.serve.resilience` — production failure semantics:
  bounded-queue admission with pluggable load shedding
  (:class:`AdmissionController`), graceful degradation under sustained
  overload, the per-tick :class:`TickJournal`, and the
  :class:`ServeSupervisor` warm-restart loop (a fatal tick exception
  rolls back to the last journaled tick; every submitted request reaches
  exactly one terminal status). Per-request deadlines live on
  :class:`Request` (``deadline_ms``) and are swept every tick.
- :mod:`~apex_tpu.serve.fleet` — :class:`FleetController`: the control
  plane above N engine replicas (thread-backed so CPU tier-1 fakes a
  pod) — heartbeat replica health (:class:`ReplicaRegistry`),
  least-loaded + burn-rate-aware routing with bounded retry and hedged
  dispatch, failover re-dispatch off dead replicas (exactly-once
  terminal status by request id), and drain/rolling restart that never
  drops admitting capacity below N-1.
- :mod:`~apex_tpu.serve.metrics` — :class:`ServeMetrics`: live per-tenant
  accounting (bounded-cardinality counters, TTFT/latency histograms,
  occupancy gauges) into an :class:`apex_tpu.monitor.export.MetricsRegistry`
  plus per-tick SLO burn-rate evaluation — the layer
  ``--metrics-port``/``--metrics-snapshot`` scrape and merge.
- :mod:`~apex_tpu.serve.tp` — tensor-parallel serving: shard params and
  the KV pool on the HEAD axis over a ``NamedSharding`` mesh and lower
  the one decode step (and each prefill bucket) under ``shard_map`` —
  one compile per mesh shape, with per-layer collectives overlapped
  TokenWeave-style (``tp_sync="overlap"``) or relaxed
  (``tp_sync="relaxed"``), and the default exact mode bit-identical in
  fp32 to the single-chip engine at equal ``block_k``.
- :mod:`~apex_tpu.serve.cli` — ``apex-tpu-serve``: load a model config,
  run a scripted or stdin request stream, print per-request stats.

See docs/serving.md for the architecture, the slot lifecycle, and the
overload/failure contracts.
"""

from apex_tpu.serve.engine import Engine, EngineConfig  # noqa: F401
from apex_tpu.serve.fleet import (EngineReplica,  # noqa: F401
                                  FleetController, FleetStats,
                                  FleetTraceHarness, ReplicaRegistry)
from apex_tpu.serve.kv_cache import (KVCache, evict_slots,  # noqa: F401
                                     init_cache, write_token)
from apex_tpu.serve.metrics import ServeMetrics  # noqa: F401
from apex_tpu.serve.resilience import (SHED_POLICIES,  # noqa: F401
                                       AdmissionController,
                                       ServeSupervisor, TickJournal)
from apex_tpu.serve.scheduler import (Request, ServeScheduler,  # noqa: F401
                                      ServeStats)

__all__ = [
    "Engine", "EngineConfig", "KVCache", "init_cache", "write_token",
    "evict_slots", "Request", "ServeScheduler", "ServeStats",
    "AdmissionController", "TickJournal", "ServeSupervisor",
    "SHED_POLICIES", "ServeMetrics",
    "FleetController", "EngineReplica", "ReplicaRegistry", "FleetStats",
    "FleetTraceHarness",
]
