"""Tensor-parallel serving — the mesh/sharding layer of the engine.

The serving engine goes multi-chip by sharding on the **head axis** over
a 1-D ``NamedSharding`` mesh (axis ``"tp"``):

- **model params** — the q/k/v projection columns, the attention output
  projection, and the MLP weights are sharded per head block (the qkv
  kernel is re-laid head-major first, see :func:`permute_qkv`, so a
  ``tp``-slice of the last axis is one rank's whole local q|k|v block);
  embeddings, layer norms, and biases added after a collective stay
  replicated;
- **KV cache** — both layouts shard their ``heads`` axis (axis 3 of the
  slot cache's ``[n_layer, num_slots, max_len, heads, head_dim]`` and of
  the paged pool's ``[n_layer, num_pages, page_size, heads, head_dim]``);
  ``lengths`` and the **page table stay replicated data** — page indices
  address every rank's shard simultaneously, so the host-side allocator,
  prefix index, and scheduler need zero changes;
- **the decode step** (and each pow2 prefill bucket) lowers the per-rank
  body under ``shard_map`` — admission/eviction/backfill still move only
  values, so the one-compile invariant becomes one compile **per mesh
  shape**.

Three per-layer synchronization modes (``EngineConfig.tp_sync``), all
sharing the per-rank arithmetic:

- ``"exact"`` (default, THE oracle): the cross-rank combine is pure
  **concatenation** — ``all_gather`` the per-head attention outputs (and
  the MLP hidden slices), then run the full projection matmul replicated.
  No float add ever crosses a rank boundary and column-sliced matmuls
  are per-column deterministic under XLA, so a ``tp=N`` engine is
  **bit-identical in fp32** to the single-chip engine at equal
  ``block_k`` (tier-1 asserts, greedy AND sampled). 2 all-gathers/layer.
- ``"overlap"`` (TokenWeave): Megatron row-parallel projections with the
  post-attention and post-MLP all-reduces each **split into two slot
  halves**, each half's psum interleaved with the adjacent residual-add
  + layer-norm compute so XLA's async collectives can hide it behind
  compute on real hardware. 4 half-psums/layer; partial sums reorder
  float adds, so ±ulp vs exact (never bit-claimed).
- ``"relaxed"`` (partially-synchronized activations, opt-in): the
  post-attention all-reduce is **deferred across the norm** — each rank's
  MLP runs on its partially-synchronized residual (local attention
  partial only) and ONE combined all-reduce per layer lands attention +
  MLP contributions together. Halves the collective count again
  (2 half-psums/layer); an approximation by construction — quality is
  checkpoint-dependent, which is why it is opt-in and the exact mode
  stays the oracle.

:func:`expected_collectives` states the per-decode-step collective
contract per mode and :func:`count_collectives` verifies it against the
actual lowered StableHLO — the tier-1 overlap-seam unit holds the two
together.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

TP_AXIS = "tp"
SYNC_MODES = ("exact", "overlap", "relaxed")


def serving_mesh(tp: int, devices=None):
    """The 1-D serving mesh: the first ``tp`` devices on axis ``"tp"``.

    Tier-1 runs this on the conftest-forced multi-device CPU host (the
    ``xla_force_host_platform_device_count`` early-env hook), so sharded
    tests never depend on real chips; a real deployment passes its ICI
    slice. Raises a clear ``ValueError`` when the host has fewer devices
    than the mesh needs."""
    import jax

    from apex_tpu.parallel.mesh import make_mesh

    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) < tp:
        raise ValueError(
            f"tp={tp} needs {tp} devices, have {len(devices)} "
            f"(on CPU force a multi-device host with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={tp})")
    return make_mesh([tp], [TP_AXIS], devices[:tp])


def permute_qkv(kernel, bias, n_head: int, head_dim: int, tp: int
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Re-lay the fused qkv projection head-major for tp slicing.

    The stock kernel is ``[e, 3e] = [Wq | Wk | Wv]``: a plain tp-slice of
    the last axis would cut across the q/k/v boundary (for tp=2, rank 0
    would get all of q plus half of k). Emit instead the concatenation
    over ranks ``r`` of ``(Wq_r | Wk_r | Wv_r)`` — rank ``r``'s contiguous
    head block of each projection — so a ``P(None, "tp")`` shard IS one
    rank's local qkv and an in-rank ``split(3)`` recovers q/k/v. Pure
    column permutation: every output column's dot product is unchanged,
    which is what keeps the sharded projection bit-exact per column."""
    kernel = np.asarray(kernel)
    bias = np.asarray(bias)
    wq, wk, wv = np.split(kernel, 3, axis=1)
    bq, bk, bv = np.split(bias, 3)
    loc = (n_head // tp) * head_dim
    ks: List[np.ndarray] = []
    bs: List[np.ndarray] = []
    for r in range(tp):
        sl = slice(r * loc, (r + 1) * loc)
        ks += [wq[:, sl], wk[:, sl], wv[:, sl]]
        bs += [bq[sl], bk[sl], bv[sl]]
    return np.concatenate(ks, axis=1), np.concatenate(bs)


def unpermute_qkv(kernel, bias, n_head: int, head_dim: int, tp: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Exact inverse of :func:`permute_qkv`: gather each projection's
    per-rank head blocks back into contiguous ``[Wq | Wk | Wv]`` —
    what turns a TP-serving checkpoint back into the dense training
    layout. A pure column permutation both ways, so the round trip is
    byte-identical; the storage layer restates both directions jax-free
    (:mod:`apex_tpu.resilience.topology`), and tier-1 holds the two
    implementations bit-identical."""
    kernel = np.asarray(kernel)
    bias = np.asarray(bias)
    loc = (n_head // tp) * head_dim
    qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
    for r in range(tp):
        base = r * 3 * loc
        qs.append(kernel[:, base:base + loc])
        ks.append(kernel[:, base + loc:base + 2 * loc])
        vs.append(kernel[:, base + 2 * loc:base + 3 * loc])
        bqs.append(bias[base:base + loc])
        bks.append(bias[base + loc:base + 2 * loc])
        bvs.append(bias[base + 2 * loc:base + 3 * loc])
    return (np.concatenate(qs + ks + vs, axis=1),
            np.concatenate(bqs + bks + bvs))


def tp_param_specs(cfg, sync: str) -> Dict[str, Any]:
    """``PartitionSpec`` tree for the TP param layout of
    :func:`build_tp_params` (same dict structure, spec leaves).

    The head-sharded leaves: qkv kernel/bias (permuted layout), the MLP
    fc rows. The attention output projection and the MLP proj are
    sharded only in the psum modes — the exact mode gathers activations
    and runs those matmuls replicated-full, which is what makes its
    combine pure concatenation."""
    from jax.sharding import PartitionSpec as P

    rep1, rep2 = P(), P(None, None)
    gathered = sync == "exact"
    block = {
        "ln_1": {"weight": rep1, "bias": rep1},
        "ln_2": {"weight": rep1, "bias": rep1},
        "attn_qkv": {"kernel": P(None, TP_AXIS), "bias": P(TP_AXIS)},
        "attn_out": {"kernel": rep2 if gathered else P(TP_AXIS, None),
                     "bias": rep1},
        "mlp_fc_w": P(TP_AXIS, None),
        "mlp_fc_b": P(TP_AXIS),
        "mlp_proj_w": rep2 if gathered else P(None, TP_AXIS),
        "mlp_proj_b": rep1,
    }
    specs: Dict[str, Any] = {
        "wte": rep2, "wpe": rep2,
        "ln_f": {"weight": rep1, "bias": rep1},
    }
    for i in range(cfg.n_layer):
        specs[f"h_{i}"] = block
    return specs


def build_tp_params(cfg, params, tp: int, sync: str, mesh):
    """The sharded serving param tree: the standard flax GPT-2 pytree
    re-laid for head-axis tp and ``device_put`` onto the mesh per
    :func:`tp_param_specs`. Returns ``(tp_params, specs)``.

    Only the qkv projection changes LAYOUT (head-major permutation);
    every other leaf keeps its bytes and is merely placed — sharded
    where a rank owns a head block, replicated otherwise."""
    import jax
    from jax.sharding import NamedSharding

    p = params["params"] if "params" in params else params
    h = cfg.n_head
    d = cfg.n_embd // h
    tree: Dict[str, Any] = {
        "wte": np.asarray(p["wte"]), "wpe": np.asarray(p["wpe"]),
        "ln_f": {k: np.asarray(v) for k, v in p["ln_f"].items()},
    }
    for i in range(cfg.n_layer):
        blk = p[f"h_{i}"]
        qkv_k, qkv_b = permute_qkv(blk["attn_qkv"]["kernel"],
                                   blk["attn_qkv"]["bias"], h, d, tp)
        tree[f"h_{i}"] = {
            "ln_1": {k: np.asarray(v) for k, v in blk["ln_1"].items()},
            "ln_2": {k: np.asarray(v) for k, v in blk["ln_2"].items()},
            "attn_qkv": {"kernel": qkv_k, "bias": qkv_b},
            "attn_out": {"kernel": np.asarray(blk["attn_out"]["kernel"]),
                         "bias": np.asarray(blk["attn_out"]["bias"])},
            "mlp_fc_w": np.asarray(blk["mlp_fc_w"]),
            "mlp_fc_b": np.asarray(blk["mlp_fc_b"]),
            "mlp_proj_w": np.asarray(blk["mlp_proj_w"]),
            "mlp_proj_b": np.asarray(blk["mlp_proj_b"]),
        }
    specs = tp_param_specs(cfg, sync)

    def place(leaf, spec):
        # explicit recursion, not jax.tree.map: PartitionSpec flattens
        # as a pytree on some jax versions, which would tear the spec
        # tree's structure out from under a joint map
        if isinstance(leaf, dict):
            return {k: place(v, spec[k]) for k, v in leaf.items()}
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return place(tree, specs), specs


def expected_collectives(n_layer: int, sync: str) -> Dict[str, int]:
    """The per-decode-step collective CONTRACT per sync mode — what the
    lowered step must contain (tier-1 holds this against
    :func:`count_collectives` of the actual StableHLO):

    - ``exact``: 2 all-gathers per layer (post-attention heads, MLP
      hidden), zero all-reduces — the combine is concatenation.
    - ``overlap``: 2 logical all-reduces per layer, each split into two
      slot-half psums (TokenWeave) = 4 all-reduces, zero gathers.
    - ``relaxed``: ONE deferred all-reduce per layer (attention partial +
      MLP partial land together), split in two halves = 2 all-reduces.

    Delegates to ``monitor/costs.py:expected_collective_ops`` — the cost
    ledger prices collective bytes from the SAME contract, so the two
    spellings can never diverge.
    """
    from apex_tpu.monitor import costs

    return costs.expected_collective_ops(n_layer, sync)


def count_collectives(stablehlo_text: str) -> Dict[str, int]:
    """Count collective ops in a lowered module's StableHLO text — the
    verifier side of :func:`expected_collectives` (pre-XLA-pass text, so
    only the shard_map-explicit collectives count, never a compiler
    resharding). Delegates to ``monitor/costs.py:collective_counts``
    (the generalized ledger walk owns the spelling)."""
    from apex_tpu.monitor import costs

    return costs.collective_counts(stablehlo_text)


def rank_snapshots(engine, meta: Optional[Dict[str, Any]] = None
                   ) -> List[Dict[str, Any]]:
    """One mergeable metrics snapshot per TP rank — the PR-10
    ``merge_snapshots`` seam used for its designed purpose: each rank
    reports its OWN shard (local KV bytes, local heads, its collective
    traffic), and the fleet view is the exact fold:

    - ``serve_tp_ranks`` gauge (agg sum, 1 per rank) → mesh size,
    - ``serve_tp_rank_heads`` gauge (agg sum) → the model's ``n_head``,
    - ``serve_tp_rank_kv_bytes`` gauge (agg sum) → the engine's total
      ``kv_cache_bytes``,
    - ``serve_tp_rank_collectives_total`` counter → fleet-wide collective
      ops executed (decode calls × the per-step contract, per rank).

    In a real multi-host deployment each host writes its own rank file;
    the fake-multihost tier-1 writes all of them from one process and
    folds them through ``tools/metrics_merge.py`` identically."""
    from apex_tpu.monitor.export import MetricsRegistry

    tp = engine.tp
    per_step = sum(expected_collectives(engine.model_cfg.n_layer,
                                        engine.config.tp_sync).values())
    docs = []
    for r in range(tp):
        reg = MetricsRegistry()
        reg.gauge("serve_tp_ranks",
                  "TP mesh ranks reporting (fleet view: mesh size)").set(1)
        reg.gauge("serve_tp_rank_heads",
                  "attention heads resident on this rank").set(
            engine.model_cfg.n_head // tp)
        reg.gauge("serve_tp_rank_kv_bytes",
                  "KV cache bytes resident on this rank").set(
            engine.kv_cache_bytes // tp)
        reg.counter(
            "serve_tp_rank_collectives_total",
            "collective ops this rank executed in decode steps").inc(
            engine.decode_calls * per_step)
        docs.append(reg.snapshot(
            meta={**(meta or {}), "tp_rank": r, "tp": tp,
                  "tp_sync": engine.config.tp_sync}))
    return docs
