"""Production failure semantics for the serving stack — admission
control, load shedding, graceful degradation, and crash-recovering warm
restart.

The PR-5 scheduler is fair-weather: an unbounded FIFO queue, no deadline
anywhere, and a fatal exception in the jitted step kills every in-flight
request (the flight recorder dumps a postmortem and the process dies).
This module composes the pieces PRs 1-7 already landed into the four
contracts a production front line needs — all of them mesh-shape-agnostic
(nothing here knows the engine's device layout, so the coming
tensor-parallel engine inherits every one for free):

- **Admission control & load shedding** — :class:`AdmissionController`
  bounds the scheduler's backlog (``max_queue``) and picks who pays when
  it overflows: ``reject-newest`` (classic tail drop), ``shed-oldest``
  (drop the request that has already waited longest — its deadline is the
  most doomed), or ``priority`` (shed the lowest-priority queued request
  strictly below the newcomer). Every shed/reject is a *terminal*,
  accounted, retriable status (``serve_request_rejected`` on the bus) —
  never a hang.
- **Graceful degradation** — under *sustained* overload (queue depth at
  the high watermark, or HBM allocator pressure from the PR-6
  ``hbm_snapshot`` sampling, for ``sustain_ticks`` consecutive ticks) the
  controller clamps admitted requests' ``max_new_tokens`` so the server
  sheds work before it sheds requests; ``serve_degraded_mode`` records
  each transition.
- **Warm restart** — :class:`TickJournal` keeps the last consistent
  end-of-tick snapshot of all scheduler request metadata (prompt ids,
  generated tokens, per-slot progress, the engine's sampling state and
  PRNG key path). ``ServeScheduler.recover()`` rebuilds device state by
  re-prefilling each surviving slot's accepted prefix through the
  existing bucketed prefill — bit-exact by the PR-5 prefill/decode
  invariant — and restores the journaled PRNG key, so surviving streams
  continue exactly where they left off. ``Engine.decode_traces`` must not
  grow across a recovery (tier-1 asserts).
- **Supervision** — :class:`ServeSupervisor` wraps ``scheduler.run()``
  with bounded retry + exponential backoff; when the budget is exhausted
  it drains every in-flight/queued request to a terminal rejected/evicted
  status (without touching the dead engine) and re-raises. Under any
  seeded :class:`~apex_tpu.resilience.fault_injection.FaultInjector`
  schedule, every submitted request reaches exactly one terminal status —
  the chaos invariant tier-1 proves.

Deadlines themselves live on :class:`~apex_tpu.serve.scheduler.Request`
(``deadline_ms``) and are swept by the scheduler every tick with
monotonic clocks (apexlint APX005); the journal's on-disk form commits
via ``.tmp`` + ``os.replace`` (APX004). See docs/serving.md "Overload
and failure semantics".
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Tuple

# shed policies: who pays when the admission queue is full
REJECT_NEWEST = "reject-newest"
SHED_OLDEST = "shed-oldest"
PRIORITY = "priority"
SHED_POLICIES = (REJECT_NEWEST, SHED_OLDEST, PRIORITY)

JOURNAL_SCHEMA_VERSION = 1


class AdmissionController:
    """Bounded-queue admission, shed policy, and degraded-mode tracking.

    Pure policy: every method is called by the scheduler under its own
    lock (submit-time decisions from :meth:`on_submit`, per-tick
    bookkeeping from :meth:`on_tick`), so the controller holds no lock
    and no thread ever races it. ``max_queue`` bounds the *backlog* (the
    admission queue the scheduler drains into free slots); a workload
    that submits its whole request list before ``run()`` should size it
    at least as large as the burst it wants queued.

    Degradation fires only when ``degraded_max_new_tokens`` is set: once
    the overload signal — ``queue_depth >= queue_high`` (default
    ``ceil(queue_high_frac * max_queue)``), HBM allocator usage at
    ``hbm_frac_high`` of the device limit (fed from the PR-6
    ``hbm_snapshot`` sampling via :meth:`note_hbm`), or the paged KV
    pool's free-page fraction at or below ``pool_frac_low`` (fed from
    the scheduler via :meth:`note_pool`) — holds for ``sustain_ticks``
    consecutive ticks, newly admitted requests have ``max_new_tokens``
    clamped until the signal clears for the same number of ticks. A
    one-tick spike never flips the mode. Clamping admitted budgets is
    doubly effective on a paged engine: the budget sizes the page
    reservation, so degradation directly relieves the pool pressure
    that triggered it.
    """

    def __init__(self, max_queue: Optional[int] = None,
                 shed_policy: str = REJECT_NEWEST, *,
                 degraded_max_new_tokens: Optional[int] = None,
                 queue_high: Optional[int] = None,
                 queue_high_frac: float = 0.75,
                 sustain_ticks: int = 4,
                 hbm_frac_high: float = 0.92,
                 pool_frac_low: float = 0.05):
        if shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"shed_policy {shed_policy!r} not in {SHED_POLICIES}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if degraded_max_new_tokens is not None \
                and degraded_max_new_tokens < 1:
            raise ValueError("degraded_max_new_tokens must be >= 1")
        self.max_queue = max_queue
        self.shed_policy = shed_policy
        self.degraded_max_new_tokens = degraded_max_new_tokens
        if queue_high is None and max_queue is not None:
            queue_high = max(1, math.ceil(queue_high_frac * max_queue))
        self.queue_high = queue_high
        self.sustain_ticks = max(1, int(sustain_ticks))
        self.hbm_frac_high = float(hbm_frac_high)
        self.pool_frac_low = float(pool_frac_low)
        self.degraded = False
        self._hot_ticks = 0
        self._cool_ticks = 0
        self._hbm_frac: Optional[float] = None
        self._pool_free_frac: Optional[float] = None

    # ---- submit-time decisions -----------------------------------------
    def on_submit(self, queue, req) -> Tuple[str, Optional[Any]]:
        """Admission verdict for ``req`` against the current backlog:
        ``("admit", None)``, ``("admit", victim)`` (shed ``victim`` from
        the queue to make room), or ``("reject", None)``."""
        if self.max_queue is None or len(queue) < self.max_queue:
            return ("admit", None)
        if self.shed_policy == SHED_OLDEST:
            return ("admit", queue[0])
        if self.shed_policy == PRIORITY:
            # oldest of the lowest-priority queued requests (min() keeps
            # the first minimal element; deque order is submit order)
            victim = min(queue, key=lambda r: r.priority)
            if victim.priority < req.priority:
                return ("admit", victim)
        return ("reject", None)

    # ---- degraded mode --------------------------------------------------
    def note_hbm(self, stats: Optional[Dict[str, int]]) -> None:
        """Feed the latest sampled ``hbm_snapshot`` allocator stats (the
        scheduler forwards its MemoryAccountant's last sample)."""
        if not stats:
            return
        limit = stats.get("bytes_limit")
        if limit:
            self._hbm_frac = stats.get("bytes_in_use", 0) / float(limit)

    def note_pool(self, free_frac: Optional[float]) -> None:
        """Feed the paged KV pool's free-page fraction (the scheduler
        forwards ``Engine.free_page_frac`` per tick on paged engines) —
        the low-watermark overload signal for KV capacity."""
        if free_frac is not None:
            self._pool_free_frac = float(free_frac)

    def overloaded(self, queue_depth: int) -> bool:
        if self.queue_high is not None and queue_depth >= self.queue_high:
            return True
        if (self._pool_free_frac is not None
                and self._pool_free_frac <= self.pool_frac_low):
            return True
        return (self._hbm_frac is not None
                and self._hbm_frac >= self.hbm_frac_high)

    def on_tick(self, queue_depth: int) -> Optional[bool]:
        """Per-tick degraded-mode bookkeeping. Returns ``True`` on the
        tick the mode is entered, ``False`` on the tick it clears, and
        ``None`` when nothing changed (the common case)."""
        if self.degraded_max_new_tokens is None:
            return None
        if self.overloaded(queue_depth):
            self._hot_ticks += 1
            self._cool_ticks = 0
        else:
            self._cool_ticks += 1
            self._hot_ticks = 0
        if not self.degraded and self._hot_ticks >= self.sustain_ticks:
            self.degraded = True
            return True
        if self.degraded and self._cool_ticks >= self.sustain_ticks:
            self.degraded = False
            return False
        return None

    def clamp(self, max_new_tokens: int) -> int:
        """The admitted token budget under the current mode."""
        if self.degraded and self.degraded_max_new_tokens is not None:
            return min(max_new_tokens, self.degraded_max_new_tokens)
        return max_new_tokens


class TickJournal:
    """The last consistent end-of-tick serving snapshot, host-side.

    The scheduler records a snapshot at the top of the first tick (the
    pre-traffic baseline a crash on the very first decode step recovers
    to) and at the end of every successful tick thereafter: per-slot
    request metadata (prompt ids, generated tokens — *copies*, so a
    half-applied crashing tick can never poison recovery), the queued
    request list, and the engine's sampling state (host lengths, last
    tokens, and the PRNG key — the key path that makes a sampled stream
    replay bit-for-bit). Only the latest snapshot is kept: recovery is a
    rollback to the last consistent tick, not a history replay.

    ``path=`` additionally persists a serializable view every ``every``
    ticks for postmortem analysis (atomic ``.tmp`` + ``os.replace``, the
    repo-wide APX004 durability contract). Warm restart reads the
    in-memory snapshot — it survives the exception, not the process; a
    cross-process cold restart from the on-disk journal is ROADMAP work.
    """

    def __init__(self, path: Optional[str] = None, *, every: int = 1):
        self.path = path
        self.every = max(1, int(every))
        self.snapshot: Optional[Dict[str, Any]] = None
        self.ticks_recorded = 0

    def record(self, snap: Dict[str, Any]) -> None:
        """Install a new consistent snapshot (built by the scheduler,
        under its lock); persist on the configured cadence."""
        self.snapshot = snap
        self.ticks_recorded += 1
        if self.path is not None and self.ticks_recorded % self.every == 0:
            self.save()

    def to_payload(self) -> Dict[str, Any]:
        """The serializable (object-ref-free) view of the snapshot."""
        snap = self.snapshot
        if snap is None:
            return {"schema": JOURNAL_SCHEMA_VERSION, "empty": True}
        slots: List[Optional[Dict[str, Any]]] = []
        for ent in snap["slots"]:
            if ent is None:
                slots.append(None)
            else:
                slots.append({"request_id": str(ent["request_id"]),
                              "prompt": list(ent["prompt"]),
                              "generated": list(ent["generated"])})
        out = {
            "schema": JOURNAL_SCHEMA_VERSION,
            "decode_steps": snap["decode_steps"],
            "decode_tokens": snap["decode_tokens"],
            "engine": snap["engine"],
            "slots": slots,
            "queued": [{"request_id": str(r.request_id),
                        "prompt_tokens": len(r.tokens)}
                       for r in snap["queued"]],
        }
        # paged engines: page tables + pool refcounts + prefix-index size
        # (docs/serving.md "Paged KV pool" — the postmortem answer to
        # "where did the HBM go"; absent entirely for slot engines so
        # pre-paging journal consumers see an unchanged document)
        if snap.get("paging") is not None:
            out["paging"] = snap["paging"]
        return out

    def save(self, path: Optional[str] = None) -> str:
        """Persist the journal atomically: stage to ``.tmp``, publish
        with one ``os.replace`` — a crash mid-save leaves the previous
        complete journal, never a torn one (apexlint APX004)."""
        path = path or self.path
        if path is None:
            raise ValueError("TickJournal has no path to save to")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_payload(), f, sort_keys=True, default=str)
        os.replace(tmp, path)
        return path


class ServeSupervisor:
    """Bounded-retry warm-restart loop around ``scheduler.run()``.

    A fatal exception anywhere in a tick (the jitted decode step, the
    prefill, scheduler host code) no longer loses the fleet: the
    supervisor backs off, calls :meth:`ServeScheduler.recover` (rollback
    to the journal's last consistent tick; compiled executables are
    reused — zero decode retraces), and resumes. After ``max_restarts``
    failed recoveries it stops pretending: every still-live request is
    drained to a terminal rejected/evicted status — the engine is never
    touched again — and the last exception propagates (with a flight
    recorder attached, its postmortem dump already landed).
    """

    def __init__(self, scheduler, *, max_restarts: int = 2,
                 backoff_s: float = 0.05, backoff_factor: float = 2.0,
                 max_backoff_s: float = 2.0, sleep=time.sleep):
        if scheduler.journal is None:
            raise ValueError(
                "ServeSupervisor needs ServeScheduler(journal=TickJournal"
                "(...)): recovery replays the journal's last snapshot")
        self.scheduler = scheduler
        self.max_restarts = max(0, int(max_restarts))
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff_s = float(max_backoff_s)
        self.sleep = sleep

    def run(self, max_steps: Optional[int] = None):
        """Run to completion across at most ``max_restarts`` warm
        restarts; returns the scheduler's :class:`ServeStats`."""
        restarts = 0
        while True:
            try:
                return self.scheduler.run(max_steps=max_steps)
            except Exception as e:
                if restarts >= self.max_restarts:
                    self.scheduler.drain_and_reject("engine_failure")
                    raise
                restarts += 1
                self.sleep(min(
                    self.backoff_s * self.backoff_factor ** (restarts - 1),
                    self.max_backoff_s))
                try:
                    self.scheduler.recover(
                        error=f"{type(e).__name__}: {e}")
                except Exception:
                    # recovery itself failed (the likeliest way: the
                    # re-prefill hit the same dead runtime). The
                    # exactly-once contract still stands: drain every
                    # live request to a terminal status — engine
                    # untouched — before propagating.
                    self.scheduler.drain_and_reject("engine_failure")
                    raise
