"""Disaggregated prefill/decode serving — fleet-of-meshes role routing,
chain-hash-certified KV page streaming, and the SLO-driven autoscaler.

apex's NCCL p2p/IPC machinery exists so KV state can move between
devices without a correctness gap; the TPU-native analog is **page**
streaming between replica pools, built from invariants this repo
already pinned: page indices are rank-invariant (one index addresses
every mesh rank's shard of a page), the page table is replicated data,
``copy_page``/``install_page`` are single jitted ops, and the prefix
index's chained chunk hashes commit to an entire prompt prefix. This
module composes them into a disaggregated fleet:

- **Roles.** Each :class:`~apex_tpu.serve.fleet.EngineReplica` carries a
  role: ``prefill`` replicas run the bucketed prefill and stream the
  committed prompt pages out; ``decode`` replicas receive pages and
  serve the client stream; ``unified`` does both (a fleet with no
  prefill replicas behaves exactly like the base
  :class:`~apex_tpu.serve.fleet.FleetController`). Every replica owns
  its own engine — and with ``EngineConfig(tp=N)`` its own
  ``NamedSharding`` mesh (the fleet-of-meshes: one compile per mesh
  shape, per-rank metrics folding through ``merge_snapshots``
  unchanged).
- **The handoff.** A disaggregation-eligible request (>= one full page
  of prompt) is NOT dispatched on arrival. The controller submits a
  *prefill job* — a replica-local clone request (id
  ``"<id>#prefill"``, ``max_new_tokens=1``) — to the least-loaded
  prefill replica; the clone never enters the fleet's request table, so
  the settlement door (:meth:`FleetController._settle` drops unknown
  ids) cannot confuse it with the real request. When the clone
  completes, the prompt's full pages sit committed in the prefill
  engine's prefix index; the controller exports them
  (:meth:`Engine.export_prefix_pages` — each payload stamped with a
  transport digest), certifies each on arrival, installs the accepted
  chain prefix into ONE decode replica's pool
  (:meth:`Engine.import_prefix_pages`), and only then dispatches the
  real request to that same replica — whose admission finds the pages
  as ordinary prefix hits and scans only the tail.
- **Certification.** The receiver derives the expected chain hashes
  from the request's own prompt (:func:`~apex_tpu.serve.paging.
  chunk_hashes`) — a payload claiming any other hash is the wrong
  prefix — and recomputes the payload digest over the bytes that
  actually arrived (:func:`~apex_tpu.serve.paging.page_payload_digest`)
  — a bit flip or torn copy in flight fails it. A failed page REFUSES
  the handoff at that point in the chain (``serve_handoff_refused``);
  pages before it stay usable, and the request's admission simply finds
  a shorter prefix and re-prefills the rest locally — **bit-exact by
  the PR-5 prefill/decode invariant**, never a silent wrong token.
- **Exactly-once across the handoff.** The real request settles through
  the fleet's unchanged attempt-identity door. A prefill replica dying
  with handoffs in flight abandons them (the request dispatches without
  pages — local re-prefill); a duplicate stream after failover is
  dropped by the prefix-index insert no-op (a chain hash already
  indexed installs nothing); a handoff racing a drain is flushed before
  the source may report drained (``pending_handoffs`` gates
  ``serve_replica_drained``). Every path ends in exactly one terminal
  record per request and at most one ``serve_handoff_wait`` stall
  record per handoff.
- **Autoscaler.** :class:`Autoscaler` runs on the control thread
  (``tick()`` from the pump loop — the fleet threading contract means
  it needs no lock), scaling one role between ``min_replicas`` and
  ``max_replicas`` on two pressure signals: the role's worst
  short-window SLO burn rate (PR 10) and its tightest free-page
  fraction. Hysteresis is structural — distinct up/down thresholds, a
  consecutive-evaluation streak requirement, and a post-action cooldown
  — so one noisy sample can never flap the fleet. Scale-up prefers
  warm-restarting a DRAINED standby (zero recompiles) over the cold
  ``factory`` spawn; scale-down is a rolling drain (queued work
  migrates, in-flight work finishes), never a kill.
- **Diurnal traffic.** :class:`DiurnalTraffic` generates the seeded
  millions-of-users load curve the autoscaler is proven under: a
  sinusoidal requests-per-second profile scaled from a modeled user
  population, integrated against an injectable clock so chaos tests
  replay bit-for-bit.

Chaos coverage (:class:`~apex_tpu.resilience.fault_injection.
FaultInjector`): ``kill_prefill_replica`` (handoffs abandoned, local
re-prefill fallback), ``corrupt_page_in_flight`` (certification refusal
path), ``stall_handoff`` (deferred delivery — charged to
``serve_handoff_wait``, never a wedged control thread). The tier-1
smoke mixes all three in one seeded schedule and holds greedy streams
bit-identical to a no-fault unified fleet with ``decode_traces`` delta
0 on every survivor. See docs/serving.md "Disaggregated
prefill/decode".
"""

from __future__ import annotations

import dataclasses
import math
import random
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.serve import paging
from apex_tpu.serve.fleet import (ADMITTING_STATES, REPLICA_DEAD,
                                  REPLICA_DRAINED, REPLICA_DRAINING,
                                  REPLICA_HEALTHY, EngineReplica,
                                  FleetController, FleetStats)
from apex_tpu.serve.scheduler import Request
# module-level on purpose (the fleet/scheduler precedent): a
# function-local import would re-import utils.logging after a
# sys.modules purge and publish to a bus no collection-time subscriber
# sees
from apex_tpu.utils.logging import publish_event

CLONE_SUFFIX = "#prefill"

# handoff lifecycle (control-thread-only transitions):
#   prefilling -> committed -> delivered | refused
#   prefilling | committed -> abandoned (source died / clone rejected)
HANDOFF_PREFILLING = "prefilling"
HANDOFF_COMMITTED = "committed"


class _Handoff:
    """Control-thread bookkeeping for one prefill→decode page handoff."""

    __slots__ = ("freq", "clone_id", "source_id", "state", "t0",
                 "deliver_at")

    def __init__(self, freq, clone_id: str, source_id: str, t0: float):
        self.freq = freq
        self.clone_id = clone_id
        self.source_id = source_id
        self.state = HANDOFF_PREFILLING
        self.t0 = t0
        self.deliver_at = t0


@dataclasses.dataclass
class DisaggStats(FleetStats):
    """Fleet stats plus the handoff ledger. Note ``attempts`` /
    ``per_replica`` counters on PREFILL replicas count their prefill
    jobs (the replica-local clones) — ``prefill_jobs`` carries the
    total so the two views reconcile: real-request completions =
    attempts completed − prefill jobs completed."""

    handoffs: int = 0
    handoffs_delivered: int = 0
    handoffs_refused: int = 0
    handoffs_abandoned: int = 0
    pages_migrated: int = 0
    prefill_jobs: int = 0

    def summary(self) -> Dict[str, Any]:
        out = super().summary()
        out.update({
            "handoffs": self.handoffs,
            "handoffs_delivered": self.handoffs_delivered,
            "handoffs_refused": self.handoffs_refused,
            "handoffs_abandoned": self.handoffs_abandoned,
            "pages_migrated": self.pages_migrated,
            "prefill_jobs": self.prefill_jobs,
        })
        return out


class DisaggController(FleetController):
    """:class:`~apex_tpu.serve.fleet.FleetController` with role-aware
    routing and the prefill→decode page handoff.

    With no ``prefill``-role replicas the controller degrades to the
    base router exactly (every override is gated on :attr:`disagg`).
    With them: real requests route only to ``decode``/``unified``
    replicas; disaggregation-eligible requests (>= one full page of
    prompt, a prefill replica admitting) go through the handoff state
    machine in :meth:`pump` before their first real dispatch. All
    handoff state lives on the control thread — the fleet threading
    contract — so none of it needs a lock."""

    def __init__(self, replicas: Sequence[EngineReplica], **kwargs: Any):
        super().__init__(replicas, **kwargs)
        prefills = [h for h in self.handles if h.role == "prefill"]
        self.disagg = bool(prefills)
        if self.disagg:
            if not any(h.role in ("decode", "unified")
                       for h in self.handles):
                raise ValueError(
                    "disaggregation needs at least one decode (or "
                    "unified) replica to stream pages into — a fleet "
                    "of only prefill replicas serves nobody")
            for h in self.handles:
                if h.engine.prefix is None:
                    raise ValueError(
                        f"replica {h.replica_id!r} ({h.role}) has no "
                        f"prefix index: disaggregation streams pages "
                        f"through it — build every replica's engine "
                        f"with page_size + prefix_cache=True")
            sizes = {int(h.engine.config.page_size)
                     for h in self.handles}
            if len(sizes) != 1:
                raise ValueError(
                    f"page_size must agree across the fleet (got "
                    f"{sorted(sizes)}): a migrated page must mean the "
                    f"same token span on both sides of the handoff")
            self.page_size: Optional[int] = sizes.pop()
        else:
            self.page_size = None
        # handoff tables (control-thread-only; keyed by REAL request id)
        self._handoffs: Dict[Any, _Handoff] = {}
        self._clone_to_req: Dict[str, Any] = {}
        self._clone_cursor: Dict[str, int] = {}
        # optional control-thread autoscaler, ticked from pump()
        self.autoscaler: Optional["Autoscaler"] = None
        # handoff counters (DisaggStats / bench entries carry them)
        self.handoffs = 0
        self.handoffs_delivered = 0
        self.handoffs_refused = 0
        self.handoffs_abandoned = 0
        self.pages_migrated = 0

    # ---------------------------------------------------------- routing
    def _route(self, exclude: Sequence[str] = ()
               ) -> Optional[EngineReplica]:
        """Real requests never land on a prefill replica — its whole
        pool budget belongs to prompt pages in transit."""
        exclude = tuple(exclude) + tuple(
            h.replica_id for h in self.handles if h.role == "prefill")
        return super()._route(exclude)

    def _route_prefill(self) -> Optional[EngineReplica]:
        """Least-loaded admitting prefill replica (healthy preferred,
        index tiebreak — the same policy shape as the real router)."""
        states = self.registry.states()
        cands = [h for h in self.handles
                 if h.role == "prefill" and not h.crashed
                 and states.get(h.replica_id) in ADMITTING_STATES]
        if not cands:
            return None
        healthy = [h for h in cands
                   if states[h.replica_id] == REPLICA_HEALTHY]
        pool = healthy or cands
        return min(pool, key=lambda h: (h.load(), h.index))

    def _dispatch_new(self, freq, now: float) -> None:
        """Interpose the handoff: an eligible fresh request prefills
        remotely first; everything else (short prompts, no prefill
        capacity, unified fleets) takes the base route-or-pend path."""
        if self.disagg and freq.spec.request_id not in self._handoffs:
            if len(freq.spec.tokens) >= self.page_size:
                source = self._route_prefill()
                if source is not None:
                    self._begin_handoff(freq, source, now)
                    return
        super()._dispatch_new(freq, now)

    # ---------------------------------------------------------- handoff
    def _begin_handoff(self, freq, source: EngineReplica,
                       now: float) -> None:
        spec = freq.spec
        clone_id = f"{spec.request_id}{CLONE_SUFFIX}"
        # the clone is a replica-LOCAL prefill job: one sampled token
        # (prefill's own epilogue — zero decode steps), no deadline (the
        # real request's deadline governs the real attempt; an expiring
        # handoff resolves through abandonment, not eviction racing)
        clone = Request(request_id=clone_id, tokens=list(spec.tokens),
                        max_new_tokens=1, priority=spec.priority,
                        tenant=spec.tenant)
        ho = _Handoff(freq, clone_id, source.replica_id, now)
        self._handoffs[spec.request_id] = ho
        self._clone_to_req[clone_id] = spec.request_id
        self.handoffs += 1
        source.pending_handoffs += 1
        # a rejected submit leaves a terminal rejected clone record —
        # the clone scan abandons the handoff from there
        source.scheduler.submit(clone)
        source.publish_progress()

    def pump(self) -> None:
        if self.disagg:
            self._pump_handoffs(self._clock())
        super().pump()
        if self.autoscaler is not None:
            self.autoscaler.tick()

    def _pump_handoffs(self, now: float) -> None:
        # 1) clone completions: committed (stall consulted once, at
        #    commit) or abandoned (the prefill side shed/evicted it)
        for h in self.handles:
            if h.role != "prefill" or not h.reachable:
                continue
            cursor = self._clone_cursor.get(h.replica_id, 0)
            if h.done_count == cursor:
                continue        # lock-free gate, as in _harvest
            done, self._clone_cursor[h.replica_id] = \
                h.scheduler.done_since(cursor)
            for req in done:
                rid = self._clone_to_req.get(req.request_id)
                ho = self._handoffs.get(rid) if rid is not None else None
                if ho is None or ho.state != HANDOFF_PREFILLING \
                        or ho.source_id != h.replica_id:
                    continue    # stale clone of an already-resolved handoff
                if req.state == "completed":
                    ho.state = HANDOFF_COMMITTED
                    stall = self.injector.handoff_stall_due() \
                        if self.injector is not None else 0.0
                    ho.deliver_at = now + stall
                else:
                    self._abandon(ho, now)
        # 2) sweep every live handoff: cancelled requests, dead sources,
        #    due deliveries (a DRAINING source flushes immediately — its
        #    committed pages must land before it may report drained)
        for rid in list(self._handoffs):
            ho = self._handoffs.get(rid)
            if ho is None:
                continue
            if ho.freq.record is not None:
                # the request settled without us (fleet-wide drain shed,
                # total-loss synthetic record): cancel the handoff
                self._cancel(ho, now)
                continue
            source = self._by_id[ho.source_id]
            src_state = self.registry.state(ho.source_id)
            if source.crashed or src_state == REPLICA_DEAD:
                # prefill completed (or not) on a dying replica: its
                # memory is gone either way — abandon, dispatch without
                # pages, re-prefill locally (bit-exact)
                self._abandon(ho, now)
                continue
            if ho.state == HANDOFF_COMMITTED and \
                    (now >= ho.deliver_at
                     or src_state == REPLICA_DRAINING):
                self._deliver(ho, source, now)

    def _deliver(self, ho: _Handoff, source: EngineReplica,
                 now: float) -> None:
        target = self._route()
        if target is None:
            return      # no decode replica admitting: retry next pump
        spec = ho.freq.spec
        payloads = source.scheduler.export_prefix_pages(
            list(spec.tokens))
        # in-flight corruption (chaos): flip one bit of the K payload
        # AFTER the digest was stamped — exactly what a real transport
        # fault looks like to the receiver
        if self.injector is not None:
            for p in payloads:
                if self.injector.page_corrupt_due():
                    k = np.array(p["k"], copy=True)
                    raw = bytearray(k.tobytes())
                    raw[0] ^= 0x01
                    p["k"] = np.frombuffer(
                        bytes(raw), dtype=k.dtype).reshape(k.shape)
        # certification: expected chain hashes derive from the
        # request's OWN prompt — the receiver trusts nothing the wire
        # claims; the first failed page truncates the accepted chain
        expected = paging.chunk_hashes(list(spec.tokens),
                                       int(self.page_size))
        target_codec = getattr(target.engine, "kv_quant", None)
        accepted: List[Dict[str, Any]] = []
        refused_at = None
        refused_reason = None
        for i, p in enumerate(payloads):
            k_np = np.asarray(p["k"])
            v_np = np.asarray(p["v"])
            if p.get("codec") != target_codec:
                # quantization provenance mismatch: the bytes may be
                # pristine, but the target pool would misread them
                # (codec bytes as fp32 or vice versa) — refuse the whole
                # chain and fall back to local re-prefill, which is
                # exact for the target's OWN codec by construction
                refused_at, refused_reason = i, "quant_codec"
                break
            if i >= len(expected) or p["chain_hash"] != expected[i]:
                refused_at, refused_reason = i, "chain_hash"
                break
            # quantized pages certify codes ‖ scales in ONE digest: a
            # flipped scale bit is refused exactly like a payload bit
            scale_bytes = ()
            if p.get("codec") is not None:
                scale_bytes = (np.asarray(p["k_scale"]).tobytes(),
                               np.asarray(p["v_scale"]).tobytes())
            if paging.page_payload_digest(
                    p["chain_hash"], k_np.tobytes(), v_np.tobytes(),
                    *scale_bytes) != p["digest"]:
                refused_at, refused_reason = i, "digest"
                break
            accepted.append(p)
        installed = {"installed": 0, "duplicate": 0, "no_capacity": 0}
        if accepted:
            installed = target.scheduler.import_prefix_pages(accepted)
        self.pages_migrated += installed["installed"]
        for i in range(installed["installed"]):
            publish_event(
                "serve_page_migrated", request_id=spec.request_id,
                from_replica=source.replica_id,
                to_replica=target.replica_id, page_index=i)
        if refused_at is not None:
            self.handoffs_refused += 1
            publish_event(
                "serve_handoff_refused", level="warning",
                request_id=spec.request_id, page_index=refused_at,
                reason=refused_reason, from_replica=source.replica_id,
                to_replica=target.replica_id)
            if refused_reason == "quant_codec":
                publish_event(
                    "serve_quant_fallback", level="warning",
                    request_id=spec.request_id,
                    source_codec=payloads[refused_at].get("codec"),
                    target_codec=target_codec,
                    from_replica=source.replica_id,
                    to_replica=target.replica_id)
            self._resolve(ho, "refused", now)
        else:
            self.handoffs_delivered += 1
            self._resolve(ho, "delivered", now)
        # the real dispatch goes to the SAME replica the pages landed
        # in — its admission finds them as prefix hits; a refused
        # (or duplicate-truncated) chain just means a longer local tail
        self._submit_attempt(ho.freq, target, now)

    def _abandon(self, ho: _Handoff, now: float) -> None:
        self.handoffs_abandoned += 1
        self._resolve(ho, "abandoned", now)
        # dispatch with no pages: the decode replica re-prefills the
        # whole prompt locally — bit-exact by the PR-5 invariant
        super()._dispatch_new(ho.freq, now)

    def _cancel(self, ho: _Handoff, now: float) -> None:
        """The request settled elsewhere: tear the handoff down without
        dispatching (exactly-once: a settled request never re-enters)."""
        source = self._by_id[ho.source_id]
        if ho.state == HANDOFF_PREFILLING and source.reachable:
            source.scheduler.abort(ho.clone_id)
            source.publish_progress()
        self._resolve(ho, "cancelled", now)

    def _resolve(self, ho: _Handoff, outcome: str, now: float) -> None:
        """Exactly one resolution per handoff: pop the tables, release
        the source's drain gate, charge the wait."""
        self._handoffs.pop(ho.freq.spec.request_id, None)
        self._clone_to_req.pop(ho.clone_id, None)
        source = self._by_id[ho.source_id]
        source.pending_handoffs = max(0, source.pending_handoffs - 1)
        publish_event(
            "serve_handoff_wait",
            seconds=round(max(now - ho.t0, 0.0), 6),
            request_id=ho.freq.spec.request_id, outcome=outcome,
            source=ho.source_id)

    # ------------------------------------------------------------- stats
    def stats(self) -> DisaggStats:
        base = super().stats()
        kw = {f.name: getattr(base, f.name)
              for f in dataclasses.fields(FleetStats)}
        return DisaggStats(handoffs=self.handoffs,
                           handoffs_delivered=self.handoffs_delivered,
                           handoffs_refused=self.handoffs_refused,
                           handoffs_abandoned=self.handoffs_abandoned,
                           pages_migrated=self.pages_migrated,
                           prefill_jobs=self.handoffs, **kw)


class Autoscaler:
    """SLO-driven per-role replica autoscaling on the control thread.

    ``tick()`` evaluates two pressure signals over the role's admitting
    replicas — the worst short-window SLO burn rate
    (:meth:`EngineReplica.burn_short_max`, PR 10) and the tightest
    free-page fraction (:attr:`Engine.free_page_frac`) — and scales
    between ``min_replicas`` and ``max_replicas``:

    - **up** when burn >= ``up_burn`` OR free pages <= ``up_free_frac``:
      prefer warm-restarting a DRAINED standby of the role
      (:meth:`FleetController.restart_replica` — zero recompiles), else
      cold-spawn via ``factory`` (a zero-arg callable returning a
      started-ready :class:`EngineReplica`;
      :meth:`FleetController.add_replica` admits it).
    - **down** when burn <= ``down_burn`` AND free pages >=
      ``down_free_frac``: rolling drain of the least-loaded replica
      (``drain(wait=False)`` — queued work migrates, in-flight work
      finishes, the drained standby becomes the next scale-up's warm
      restart).

    **Hysteresis, structurally.** Three independent guards keep it from
    flapping: (1) the up and down thresholds are disjoint bands — a
    signal between them scales nothing; (2) a direction must hold for
    ``evals`` CONSECUTIVE ticks (one noisy sample resets the streak);
    (3) after any action the ``cooldown_s`` window rejects further
    actions entirely. Total actions over a window W are therefore
    bounded by ``W / cooldown_s`` whatever the traffic does — the
    tier-1 diurnal test asserts exactly this bound. Capacity can never
    leave ``[min_replicas, max_replicas]``: down is refused at min, up
    at max.

    Runs strictly on the fleet's control thread (tick it from the pump
    loop, or attach as ``DisaggController.autoscaler``), so its tables
    need no lock — the same contract every controller table relies on.
    """

    def __init__(self, fleet: FleetController, *, role: str = "decode",
                 min_replicas: int = 1, max_replicas: int = 4,
                 factory=None, up_burn: float = 1.0,
                 down_burn: float = 0.25, up_free_frac: float = 0.1,
                 down_free_frac: float = 0.5, evals: int = 2,
                 cooldown_s: float = 0.25, clock=None):
        if role not in EngineReplica.ROLES:
            raise ValueError(
                f"role={role!r} must be one of {EngineReplica.ROLES}")
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas} / {max_replicas}")
        if not 0 <= down_burn < up_burn:
            raise ValueError(
                f"need 0 <= down_burn < up_burn (disjoint hysteresis "
                f"bands), got {down_burn} / {up_burn}")
        if not 0 <= up_free_frac < down_free_frac <= 1:
            raise ValueError(
                f"need 0 <= up_free_frac < down_free_frac <= 1, got "
                f"{up_free_frac} / {down_free_frac}")
        self.fleet = fleet
        self.role = role
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.factory = factory
        self.up_burn = float(up_burn)
        self.down_burn = float(down_burn)
        self.up_free_frac = float(up_free_frac)
        self.down_free_frac = float(down_free_frac)
        self.evals = max(1, int(evals))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock or fleet._clock
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0
        self.spawned = 0

    # ------------------------------------------------------------ signals
    def _role_handles(self) -> List[EngineReplica]:
        return [h for h in self.fleet.handles if h.role == self.role]

    def active(self) -> List[EngineReplica]:
        states = self.fleet.registry.states()
        return [h for h in self._role_handles()
                if not h.crashed
                and states.get(h.replica_id) in ADMITTING_STATES]

    def standbys(self) -> List[EngineReplica]:
        states = self.fleet.registry.states()
        return [h for h in self._role_handles()
                if states.get(h.replica_id) == REPLICA_DRAINED]

    def signals(self) -> Dict[str, float]:
        active = self.active()
        return {
            "burn": max((h.burn_short_max() for h in active),
                        default=0.0),
            "free_page_frac": min(
                (h.engine.free_page_frac for h in active), default=1.0),
            "active": float(len(active)),
        }

    # --------------------------------------------------------------- tick
    def tick(self) -> Optional[str]:
        """One control-loop evaluation; returns ``"up"``/``"down"`` when
        an action fired, else ``None``."""
        now = self._clock()
        sig = self.signals()
        n = int(sig["active"])
        pressure = sig["burn"] >= self.up_burn \
            or sig["free_page_frac"] <= self.up_free_frac
        quiet = sig["burn"] <= self.down_burn \
            and sig["free_page_frac"] >= self.down_free_frac
        self._up_streak = self._up_streak + 1 if pressure else 0
        self._down_streak = self._down_streak + 1 if quiet else 0
        if self._last_action_t is not None \
                and now - self._last_action_t < self.cooldown_s:
            return None
        if pressure and self._up_streak >= self.evals \
                and n < self.max_replicas:
            return self._scale_up(now, sig)
        if quiet and self._down_streak >= self.evals \
                and n > self.min_replicas:
            return self._scale_down(now, sig)
        return None

    def _scale_up(self, now: float, sig: Dict[str, float]
                  ) -> Optional[str]:
        standby = self.standbys()
        if standby:
            handle = min(standby, key=lambda h: h.index)
            self.fleet.restart_replica(handle.replica_id)   # warm: zero
            #                                                 recompiles
        elif self.factory is not None:
            handle = self.factory()
            if handle.role != self.role:
                raise ValueError(
                    f"factory built a {handle.role!r} replica; this "
                    f"autoscaler scales {self.role!r}")
            self.fleet.add_replica(handle)
            self.spawned += 1
        else:
            return None     # nothing to scale with: not an action
        self.scale_ups += 1
        self._last_action_t = now
        self._up_streak = 0
        self._down_streak = 0
        publish_event(
            "serve_autoscale_up", role=self.role,
            replica=handle.replica_id, replicas=len(self.active()),
            burn=round(sig["burn"], 4),
            free_page_frac=round(sig["free_page_frac"], 4))
        return "up"

    def _scale_down(self, now: float, sig: Dict[str, float]) -> str:
        handle = min(self.active(), key=lambda h: (h.load(), h.index))
        self.fleet.drain(handle.replica_id, wait=False)
        self.scale_downs += 1
        self._last_action_t = now
        self._up_streak = 0
        self._down_streak = 0
        publish_event(
            "serve_autoscale_down", role=self.role,
            replica=handle.replica_id, replicas=len(self.active()),
            burn=round(sig["burn"], 4),
            free_page_frac=round(sig["free_page_frac"], 4))
        return "down"


class DiurnalTraffic:
    """Seeded diurnal request generator — the millions-of-users load
    curve compressed onto a test clock.

    The modeled fleet serves ``users`` users issuing
    ``requests_per_user_per_day`` requests over a (wall-clock) day;
    this harness replays that curve over ``day_s`` seconds at
    ``capacity_scale`` of the modeled volume (the CPU fleet under test
    is a thin slice of the modeled one). The instantaneous rate is
    sinusoidal with ``peak_to_trough`` ratio, trough at phase 0:

    ``rate(x) = trough + (peak - trough) * (1 - cos(2*pi*x)) / 2``

    :meth:`due` integrates the rate between consecutive calls against
    the injected ``clock`` and emits whole requests (fractional
    residue carries over), each with a seeded prompt — same seed +
    same clock readings = the identical request stream, which is what
    lets the autoscaler chaos test replay bit-for-bit."""

    def __init__(self, *, users: int = 2_000_000,
                 requests_per_user_per_day: float = 8.0,
                 peak_to_trough: float = 4.0, day_s: float = 86400.0,
                 capacity_scale: float = 1e-4, seed: int = 0,
                 prompt_lens: Sequence[int] = (8,),
                 max_new_tokens: int = 4, vocab: int = 61,
                 id_prefix: str = "diurnal",
                 clock=time.perf_counter):
        if peak_to_trough < 1:
            raise ValueError(
                f"peak_to_trough={peak_to_trough} must be >= 1")
        mean_rps = float(users) * float(requests_per_user_per_day) \
            / 86400.0 * float(capacity_scale)
        r = float(peak_to_trough)
        self.trough_rps = 2.0 * mean_rps / (1.0 + r)
        self.peak_rps = r * self.trough_rps
        self.day_s = float(day_s)
        self.prompt_lens = list(prompt_lens)
        self.max_new_tokens = int(max_new_tokens)
        self.vocab = int(vocab)
        self.id_prefix = id_prefix
        self.rng = random.Random(seed)
        self.clock = clock
        self._t0: Optional[float] = None
        self._last_t: Optional[float] = None
        self._accum = 0.0
        self.emitted = 0

    def rate_at(self, now: float) -> float:
        """Requests per second at wall time ``now`` (0 before start)."""
        if self._t0 is None:
            return 0.0
        x = ((now - self._t0) % self.day_s) / self.day_s
        return self.trough_rps + (self.peak_rps - self.trough_rps) \
            * (1.0 - math.cos(2.0 * math.pi * x)) / 2.0

    def start(self, t0: Optional[float] = None) -> "DiurnalTraffic":
        self._t0 = self.clock() if t0 is None else float(t0)
        self._last_t = self._t0
        self._accum = 0.0
        return self

    def due(self, now: Optional[float] = None) -> List[Request]:
        """Requests that became due since the previous call (consumed).
        Trapezoidal integration of the rate curve over the elapsed
        window; sub-request residue accumulates, so long-run volume
        matches the curve whatever the polling cadence."""
        if self._t0 is None:
            raise RuntimeError("DiurnalTraffic.due() before start()")
        now = self.clock() if now is None else float(now)
        dt = max(now - self._last_t, 0.0)
        self._accum += dt * (self.rate_at(self._last_t)
                             + self.rate_at(now)) / 2.0
        self._last_t = now
        n = int(self._accum)
        self._accum -= n
        out: List[Request] = []
        for _ in range(n):
            self.emitted += 1
            plen = self.rng.choice(self.prompt_lens)
            out.append(Request(
                request_id=f"{self.id_prefix}-{self.emitted}",
                tokens=[self.rng.randrange(self.vocab)
                        for _ in range(plen)],
                max_new_tokens=self.max_new_tokens))
        return out
