"""Per-tenant serving accounting — the live-metrics adapter between the
scheduler and :mod:`apex_tpu.monitor.export` / :mod:`apex_tpu.monitor.slo`.

One :class:`ServeMetrics` object owns the serving metric families and is
called by :class:`~apex_tpu.serve.scheduler.ServeScheduler` at exactly
the points where the matching bus events publish (``metrics=None``, the
default, keeps the scheduler at zero extra work per tick — the tracer
pattern). Every hook is host python under the scheduler's lock, off the
traced path (apexlint APX001 flags a registry mutation reachable from
traced code; tier-1 scrapes a live loop and asserts ``decode_traces ==
1``).

Requests carry an optional ``tenant`` label
(:class:`~apex_tpu.serve.scheduler.Request`); unlabeled requests land
under ``default``. Cardinality is bounded at ``max_tenants`` — overflow
tenants fold into the registry's ``__other__`` series, so a tenant-id
explosion cannot grow a scrape.

The family catalog (all ``serve_*``; seconds-valued histograms):

========================================  =========  ==================
name                                      type       labels
========================================  =========  ==================
serve_requests_submitted_total            counter    tenant
serve_requests_admitted_total             counter    tenant
serve_requests_completed_total            counter    tenant
serve_requests_rejected_total             counter    tenant
serve_requests_evicted_total              counter    tenant
serve_deadline_exceeded_total             counter    tenant
serve_prefix_hits_total                   counter    tenant
serve_generated_tokens_total              counter    tenant
serve_spec_accept_rate                    histogram  tenant
serve_tokens_per_decode_step              gauge      tenant (merge: max)
serve_ttft_seconds                        histogram  tenant
serve_latency_seconds                     histogram  tenant
serve_queue_wait_seconds                  histogram  tenant
serve_decode_step_seconds                 histogram  —
serve_queue_depth                         gauge      — (merge: sum)
serve_active_slots                        gauge      — (merge: sum)
serve_resident_tokens                     gauge      — (merge: sum)
serve_free_page_frac                      gauge      — (merge: min)
serve_slo_burn_short / _long / _breached  gauge      objective (max)
========================================  =========  ==================

Tier-1 holds the per-tenant counters against the scheduler's exact
end-of-run ``summary()`` (the sums must agree) and the TTFT/latency
histogram quantiles against the exact sorted-list percentiles within the
documented bucket error. See docs/observability.md "Live metrics, SLOs,
and fleet aggregation".
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

from apex_tpu.monitor.export import MetricsRegistry

DEFAULT_TENANT = "default"


class ServeMetrics:
    """Record serving lifecycle + latency into a
    :class:`~apex_tpu.monitor.export.MetricsRegistry`, optionally feeding
    an :class:`~apex_tpu.monitor.slo.SLOTracker` whose burn rates are
    mirrored into gauges each tick."""

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 slo=None, max_tenants: int = 32):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.slo = slo
        r = self.registry
        t = ("tenant",)
        n = int(max_tenants)
        self.submitted = r.counter(
            "serve_requests_submitted_total",
            "requests entering the admission backlog", t, n)
        self.admitted = r.counter(
            "serve_requests_admitted_total",
            "requests that reached a cache slot", t, n)
        self.completed = r.counter(
            "serve_requests_completed_total",
            "requests finishing with eos/length/context", t, n)
        self.rejected = r.counter(
            "serve_requests_rejected_total",
            "requests shed by admission control (retriable)", t, n)
        self.evicted = r.counter(
            "serve_requests_evicted_total",
            "mid-stream evictions (abort/shutdown/engine_failure)", t, n)
        self.deadline = r.counter(
            "serve_deadline_exceeded_total",
            "requests expiring on their deadline_ms budget", t, n)
        self.prefix_hits = r.counter(
            "serve_prefix_hits_total",
            "admissions served partly from resident prefix pages", t, n)
        self.generated = r.counter(
            "serve_generated_tokens_total",
            "tokens generated for terminal requests", t, n)
        # speculative decoding (PR-18): accept rate is a per-(slot, step)
        # sample in [0, 1] — quantiles answer "how often do drafts land
        # for THIS tenant", which a run-total ratio hides; the gauge is
        # the live tokens-per-step multiplier the autoscaler reads
        self.spec_accept = r.histogram(
            "serve_spec_accept_rate",
            "per-step draft acceptance fraction (speculative decode)",
            t, n)
        self.tokens_per_step = r.gauge(
            "serve_tokens_per_decode_step",
            "tokens committed per decode step, last tick", t, n,
            agg="max")
        self.ttft = r.histogram(
            "serve_ttft_seconds", "submit to first token", t, n)
        self.latency = r.histogram(
            "serve_latency_seconds", "submit to terminal status", t, n)
        self.queue_wait = r.histogram(
            "serve_queue_wait_seconds", "time queued before a slot", t, n)
        self.decode_step = r.histogram(
            "serve_decode_step_seconds", "one batched decode step")
        self.queue_depth = r.gauge(
            "serve_queue_depth", "requests waiting for admission")
        self.active_slots = r.gauge(
            "serve_active_slots", "slots decoding this tick")
        self.resident_tokens = r.gauge(
            "serve_resident_tokens", "KV tokens resident across slots")
        self.free_page_frac = r.gauge(
            "serve_free_page_frac",
            "paged-pool free fraction (1.0 on slot engines)", agg="min")
        obj = ("objective",)
        self.slo_burn_short = r.gauge(
            "serve_slo_burn_short",
            "short-window error-budget burn rate", obj, agg="max")
        self.slo_burn_long = r.gauge(
            "serve_slo_burn_long",
            "long-window error-budget burn rate", obj, agg="max")
        self.slo_breached = r.gauge(
            "serve_slo_breached", "1 while the objective is breached",
            obj, agg="max")

    # ---- per-request lifecycle (caller: scheduler, under its lock) -----
    @staticmethod
    def _tenant(req) -> str:
        tenant = getattr(req, "tenant", None)
        return str(tenant) if tenant else DEFAULT_TENANT

    def on_submit(self, req) -> None:
        self.submitted.inc(tenant=self._tenant(req))

    def on_admit(self, req, wait_s: float) -> None:
        tenant = self._tenant(req)
        self.admitted.inc(tenant=tenant)
        self.queue_wait.record(wait_s, tenant=tenant)

    def on_prefix_hit(self, req, hit_tokens: int) -> None:
        self.prefix_hits.inc(tenant=self._tenant(req))

    def on_spec(self, req, *, proposed: int, accepted: int) -> None:
        """One slot's draft outcome for one verify step (speculative
        decode only; ticks where the scheduler clamped the draft to zero
        contribute no sample — there was nothing to accept)."""
        if proposed > 0:
            self.spec_accept.record(accepted / proposed,
                                    tenant=self._tenant(req))

    def on_spec_step(self, tenant_tokens: Mapping[Any, int]) -> None:
        """Tokens committed per tenant in one verify step — sets the live
        ``serve_tokens_per_decode_step`` gauge (1.0 is the one-token
        floor; > 1 is speculation paying off)."""
        for tenant, tokens in tenant_tokens.items():
            self.tokens_per_step.set(
                float(tokens),
                tenant=str(tenant) if tenant else DEFAULT_TENANT)

    def on_complete(self, req) -> None:
        tenant = self._tenant(req)
        self.completed.inc(tenant=tenant)
        self.generated.inc(len(req.generated), tenant=tenant)
        if req.ttft_s is not None:
            self.ttft.record(req.ttft_s, tenant=tenant)
        if req.latency_s is not None:
            self.latency.record(req.latency_s, tenant=tenant)
        if self.slo is not None:
            if req.ttft_s is not None:
                self.slo.observe("ttft", value=req.ttft_s)
            self.slo.observe("deadline", bad=False)
            self.slo.observe("shed", bad=False)

    def on_reject(self, req, reason: str) -> None:
        tenant = self._tenant(req)
        self.rejected.inc(tenant=tenant)
        if req.latency_s is not None:
            self.latency.record(req.latency_s, tenant=tenant)
        if self.slo is not None:
            self.slo.observe("shed", bad=True)
            # EVERY terminal status feeds every fraction window exactly
            # once, or the live denominators diverge from the documented
            # objectives (deadline_miss_frac is over TERMINAL requests;
            # check_regression derives it over submitted): a rejected
            # request is terminal and did not miss a deadline
            self.slo.observe("deadline", bad=False)

    def on_deadline(self, req) -> None:
        tenant = self._tenant(req)
        self.deadline.inc(tenant=tenant)
        self.generated.inc(len(req.generated), tenant=tenant)
        # a request that reached its first token and THEN expired still
        # witnessed a TTFT — the exact summary counts it, and under
        # deadline pressure the worst TTFTs are exactly the requests
        # that die by deadline: dropping them would make the histogram
        # (and the ttft SLO) read systematically better than the oracle
        if req.ttft_s is not None:
            self.ttft.record(req.ttft_s, tenant=tenant)
        if req.latency_s is not None:
            self.latency.record(req.latency_s, tenant=tenant)
        if self.slo is not None:
            if req.ttft_s is not None:
                self.slo.observe("ttft", value=req.ttft_s)
            self.slo.observe("deadline", bad=True)
            self.slo.observe("shed", bad=False)

    def on_evict(self, req, reason: str) -> None:
        tenant = self._tenant(req)
        self.evicted.inc(tenant=tenant)
        self.generated.inc(len(req.generated), tenant=tenant)
        # same survivorship rule as on_deadline: an evicted request that
        # got a first token is a TTFT witness the summary also counts
        if req.ttft_s is not None:
            self.ttft.record(req.ttft_s, tenant=tenant)
        if req.latency_s is not None:
            self.latency.record(req.latency_s, tenant=tenant)
        if self.slo is not None:
            if req.ttft_s is not None:
                self.slo.observe("ttft", value=req.ttft_s)
            # eviction is terminal: one good event in each fraction
            # window keeps the live denominators == terminal requests
            # (see on_reject) — an evicted request was neither shed by
            # admission nor expired on its deadline
            self.slo.observe("deadline", bad=False)
            self.slo.observe("shed", bad=False)

    # ---- per-tick ------------------------------------------------------
    def on_tick(self, *, dt_s: Optional[float], active: int,
                queue_depth: int, resident_tokens: int,
                free_page_frac: float) -> None:
        """End of one scheduler tick (``dt_s=None`` on idle ticks: no
        decode step ran, but occupancy gauges and the SLO windows must
        still move — a deadline storm can breach with zero decode
        steps)."""
        if dt_s is not None:
            self.decode_step.record(dt_s)
        self.queue_depth.set(queue_depth)
        self.active_slots.set(active)
        self.resident_tokens.set(resident_tokens)
        self.free_page_frac.set(free_page_frac)
        if self.slo is not None:
            self.slo.evaluate()
            for name, state in self.slo.summary().items():
                self.slo_burn_short.set(state["burn_short"],
                                        objective=name)
                self.slo_burn_long.set(state["burn_long"], objective=name)
                self.slo_breached.set(float(state["breached"]),
                                      objective=name)

    def summary(self) -> Dict[str, Any]:
        """A compact live view (the CLI's final summary carries it when
        metrics are armed): per-family totals plus the SLO state."""
        totals: Dict[str, float] = {}
        for fam in self.registry.families():
            if fam.kind == "counter":
                totals[fam.name] = sum(s.value for s in fam.series())
        out: Dict[str, Any] = {"totals": totals}
        if self.slo is not None:
            out["slo"] = self.slo.summary()
        return out
