"""Console entry point (``apex-tpu-serve``) — run a request stream
through the serving engine and print per-request stats.

Two request sources:

- scripted (default): ``--requests N`` seeded random prompts — the
  repeatable smoke/bench workload;
- ``--stdin``: one request per line, whitespace- or comma-separated token
  ids (the engine speaks token ids; tokenization lives with the caller).

Per request, one JSON line: ``{request_id, state, finish_reason,
prompt_tokens, new_tokens, generated, ttft_s, latency_s, tokens_per_s}``
(load-shed requests additionally carry ``"retriable": true`` — a healthy
or less-loaded replica can serve them); the final line is the aggregate
summary (tokens/s, p50/p99 per-step latency, TTFT, plus the SLO fields
``rejected`` / ``deadline_exceeded`` / ``shed_rate`` / ``restarts``).
``serve_*`` lifecycle events ride the telemetry bus —
``--telemetry-jsonl PATH`` mirrors them (and nothing else crosses the
host boundary per step beyond the sampled tokens).

Production failure semantics (docs/serving.md "Overload and failure
semantics"): ``--deadline-ms`` bounds per-request latency,
``--max-queue`` + ``--shed-policy`` bound the backlog with explicit
rejection, ``--max-restarts N`` arms the tick journal + warm-restart
supervisor so a fatal tick exception recovers instead of killing every
in-flight request.

Paged KV pool (docs/serving.md "Paged KV pool and prefix caching"):
``--page-size N`` swaps the per-slot ``max_len`` reservation for a
shared block pool (``--num-pages`` sizes it; default = same token
capacity as the slot cache, size it smaller to overcommit), and
``--prefix-cache`` shares read-only prompt-prefix pages across requests
so a repeated system prompt is prefilled once. The summary's
``prefix_hit_rate`` / ``peak_resident_tokens`` report what the pool
bought; decode still compiles exactly once (``decode_compiles``).

Tensor-parallel decode (docs/serving.md "Tensor-parallel decode"):
``--tp N`` shards the ONE engine — params and the KV pool on the head
axis — over an N-device ``NamedSharding`` mesh and lowers decode plus
each prefill bucket under ``shard_map``; the default ``--tp-sync exact``
mode is bit-identical to the single-chip engine (fp32, equal block_k),
``overlap``/``relaxed`` trade ulps/accuracy for fewer or hidden
collectives. One compile per mesh shape (``decode_compiles`` stays 1);
with ``--metrics-snapshot PATH`` each rank's shard-local view lands at
``PATH.tpK`` and the ``tools/metrics_merge.py`` fold at ``PATH.tp``.
``--tp`` composes with ``--replicas N`` as a **fleet of meshes**: each
replica owns its own N-device ``NamedSharding`` mesh (one compile per
mesh shape; per-rank metrics fold through the same merge). ``--tp-sync``
without a mesh is still refused as inert.

Live metrics and SLOs (docs/observability.md "Live metrics, SLOs, and
fleet aggregation"): ``--metrics-port`` serves Prometheus text at
``/metrics`` + a mergeable JSON snapshot at ``/metrics.json`` while the
scheduler runs, ``--metrics-snapshot PATH`` commits the snapshot
atomically at exit (the per-rank artifact ``tools/metrics_merge.py``
folds into one fleet view), ``--tenants N`` labels the scripted workload
round-robin so the per-tenant breakdown is visible, and ``--slo
NAME=VALUE`` (repeatable) arms burn-rate tracked objectives whose
breach/recovery transitions publish ``serve_slo_breach`` /
``serve_slo_recovered`` bus events.

Serving fleet (docs/serving.md "Fleet failover and draining"):
``--replicas N`` (N >= 2) runs N thread-backed engine replicas under a
:class:`~apex_tpu.serve.fleet.FleetController` — heartbeat replica
health (``--heartbeat-ms``), least-loaded routing with failover
re-dispatch off dead replicas, optional hedged dispatch
(``--hedge-ms``: a request with no terminal status after that long
fires one copy on a second replica, first terminal wins), and
``--drain-on SIGTERM`` (on SIGTERM: stop admitting, shed still-queued
requests as retriable rejections — a healthy fleet can serve them —
finish in-flight ones, exit cleanly). The summary gains ``failovers`` /
``hedge_fired`` / ``migrations``; ``--metrics-snapshot PATH`` writes one
mergeable snapshot PER replica (``PATH.rK``) plus the
``tools/metrics_merge.py`` fleet view at ``PATH`` itself.

Fleet request journeys (docs/observability.md "Fleet request
journeys"): with ``--replicas N``, ``--trace-jsonl PATH`` opens ONE
cross-replica trace per request (``fleet_queue → attempt[replica=k] →
retry/backoff → hedge → failover → terminal``, with each replica's
``queue/prefill/decode`` spans nested under its attempt) — the fleet
plane streams to ``PATH``, each replica to ``PATH.rK``, and
``tools/trace_explain.py`` merges them into per-request latency
attribution that reconciles exactly with the summary and the goodput
ledger. ``--trace-sample RATE`` head-samples the happy path
deterministically (seeded) while tail capture promotes every
bad-outcome journey in full; ``--metrics-port`` serves the merged fleet
view at ``/metrics`` plus per-replica registries at ``/metrics/rK``;
``--flight-recorder PATH`` arms one recorder per replica (``PATH.rK``,
auto-dump on that replica's death or suspect escalation with its
registry row and open spans) plus a fleet-plane recorder at ``PATH``.
Only ``--max-restarts`` remains single-scheduler wiring (exit 2 with
``--replicas > 1``), as are the fleet knobs with ``--replicas 1`` —
never silent no-ops; ``--trace-sample`` without ``--trace-jsonl`` is
equally inert and refused.

Disaggregated prefill/decode (docs/serving.md "Disaggregated
prefill/decode"): ``--roles P:D`` splits the fleet into P dedicated
prefill replicas and D decode replicas (``--replicas``, if given, must
equal P+D). Prefill replicas run the bucketed prefill and stream the
committed prompt pages into a decode replica's pool; every migrated
page is certified on arrival against the prompt's own chain hashes — a
corrupt or torn transfer refuses the handoff and the decode replica
re-prefills locally, bit-exact. Requires ``--page-size`` +
``--prefix-cache`` (pages move through the prefix index).
``--autoscale`` arms the SLO-driven control loop (needs ``--slo`` —
the burn rate is its up signal) scaling the decode pool between
``--min-replicas`` and ``--max-replicas`` by rolling drain / warm
restart; both bounds are inert (exit 2) without it.

Example::

    apex-tpu-serve --config tiny --requests 4 --max-new-tokens 8 \
        --temperature 0 --seed 0
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

import numpy as np


def _parse_roles(spec):
    """``"P:D"`` -> ``(P, D)`` with both >= 1, else None (bad spec or
    no spec — the caller owns the usage error)."""
    if spec is None:
        return None
    p, sep, d = str(spec).partition(":")
    if not sep:
        return None
    try:
        roles = (int(p), int(d))
    except ValueError:
        return None
    return roles if roles[0] >= 1 and roles[1] >= 1 else None


def _parse_line(line: str) -> List[int]:
    toks = line.replace(",", " ").split()
    return [int(t) for t in toks]


def _run_fleet(args, cfg, max_len: int, prompts, slo) -> int:
    """The ``--replicas N`` path: N thread-backed engine replicas under
    a :class:`~apex_tpu.serve.fleet.FleetController`. ``slo`` (one
    parsed tracker, or None) donates its objective DECLARATIONS — each
    replica gets its own tracker instance so burn windows never alias
    across replicas (the burn is the per-replica routing signal).

    Fleet observability (PR 13): ``--trace-jsonl`` opens one
    cross-replica journey per request (fleet file at PATH, one
    Chrome-trace per replica at PATH.rK; ``--trace-sample`` head-samples
    the happy path while tail capture promotes every bad outcome);
    ``--metrics-port`` serves the merged fleet view at ``/metrics`` and
    each replica at ``/metrics/rK``; ``--flight-recorder`` arms one
    recorder per replica (auto-dump on that replica's death/suspect
    transition, with its registry row as context) plus a fleet-level
    recorder guarding the control loop."""
    import signal as signal_mod

    from apex_tpu.serve.disagg import Autoscaler, DisaggController
    from apex_tpu.serve.engine import (Engine, EngineConfig,
                                       init_gpt2_params)
    from apex_tpu.serve.fleet import (EngineReplica, FleetController,
                                      FleetTraceHarness)
    from apex_tpu.serve.scheduler import Request

    roles = _parse_roles(args.roles)
    if roles:
        # pK prefill the prompts and stream pages; dK decode the streams
        replica_specs = [(f"p{i}", "prefill") for i in range(roles[0])] \
            + [(f"d{i}", "decode") for i in range(roles[1])]
    else:
        replica_specs = [(f"r{i}", "unified")
                         for i in range(args.replicas)]
    replica_ids = [rid for rid, _ in replica_specs]
    want_metrics = bool(args.metrics_snapshot) or slo is not None \
        or args.metrics_port is not None
    metrics_meta = registries = exporter = None
    if want_metrics:
        from apex_tpu.monitor.export import MetricsRegistry
        from apex_tpu.utils.env import capture_provenance

        metrics_meta = capture_provenance()
        registries = {rid: MetricsRegistry() for rid in replica_ids}
        if args.metrics_port is not None:
            # bound BEFORE the engines pay for params + compiles (the
            # PR-10 contract): an unbindable port must fail in
            # milliseconds with exit 2, never after trace time
            from apex_tpu.monitor.export import FleetMetricsExporter

            try:
                exporter = FleetMetricsExporter(
                    registries, port=args.metrics_port,
                    meta=metrics_meta).start()
            except OSError as e:
                print(f"apex-tpu-serve: cannot bind --metrics-port "
                      f"{args.metrics_port}: {e}", file=sys.stderr)
                return 2
            print(f"apex-tpu-serve: fleet metrics at {exporter.url} "
                  f"(merged; per-replica at /metrics/rK)",
                  file=sys.stderr)

    harness = None
    if args.trace_jsonl:
        harness = FleetTraceHarness(
            args.trace_jsonl, replica_ids,
            sample_rate=1.0 if args.trace_sample is None
            else args.trace_sample,
            sample_seed=args.seed)

    params = init_gpt2_params(cfg, seed=args.seed)
    # fleet of meshes: with --tp >= 2 EVERY replica shards its own
    # engine over its own NamedSharding mesh (one compile per mesh
    # shape; per-rank metrics fold through the same snapshot merge)
    engine_cfg = EngineConfig(num_slots=args.num_slots, max_len=max_len,
                              temperature=args.temperature,
                              top_k=args.top_k, page_size=args.page_size,
                              num_pages=args.num_pages,
                              prefix_cache=args.prefix_cache,
                              tp=args.tp, tp_sync=args.tp_sync,
                              spec_draft_len=args.spec_draft_len or 0,
                              decode_policy=args.decode_policy,
                              kv_quant=args.kv_quant)
    handles = []
    for i, (rid, role) in enumerate(replica_specs):
        try:
            engine = Engine(cfg, params, engine_cfg, seed=args.seed)
        except ValueError as e:
            print(f"apex-tpu-serve: {e}", file=sys.stderr)
            if exporter is not None:
                exporter.stop()
            if harness is not None:
                harness.close()
            return 2
        admission = metrics = None
        if args.max_queue is not None:
            from apex_tpu.serve.resilience import AdmissionController

            admission = AdmissionController(max_queue=args.max_queue,
                                            shed_policy=args.shed_policy)
        if want_metrics:
            from apex_tpu.monitor.slo import SLOTracker
            from apex_tpu.serve.metrics import ServeMetrics

            tracker = SLOTracker(slo.objectives) \
                if slo is not None else None
            metrics = ServeMetrics(registry=registries[rid], slo=tracker)
        handles.append(EngineReplica(
            rid, engine, role=role, admission=admission,
            metrics=metrics,
            tracer=harness.tracer_for(rid) if harness is not None
            else None))
    # ALWAYS pre-compile in fleet mode (--aot is implied): a prefill or
    # decode compiling inside a worker's first tick blocks that
    # replica's heartbeats for the whole trace time — seconds — which
    # the registry can only read as a death, triggering a spurious
    # fleet-wide failover before any request is served. Startup pays
    # every trace; the heartbeat window only ever measures serving.
    # EVERY reachable pow2 bucket is warmed, not just the prompt
    # lengths': a prefix-cache hit prefills only the unshared tail,
    # which lands on a smaller bucket (the bench warms identically)
    top = max(len(p) for p in prompts)
    buckets, b = [], 1
    while b < top:
        buckets.append(b)
        b *= 2
    buckets.append(top)
    for h in handles:
        h.engine.aot_compile(buckets)
    tel = None
    if args.telemetry_jsonl:
        from apex_tpu.monitor import Telemetry

        tel = Telemetry(args.telemetry_jsonl)
    # CPU-tolerant death budget (heartbeat_ms * dead_misses = 2s at the
    # default interval): the XLA CPU client serializes executions, so a
    # contended decode tick — during which the worker cannot beat — can
    # stall far past a tight window; fabricated deaths would duplicate
    # work via failover on a perfectly healthy fleet. Operators trade
    # detection latency via --heartbeat-ms (the budget scales with it).
    # DisaggController degrades to the base router with no prefill
    # replicas, so it also carries the autoscaler hook for unified
    # fleets; the plain FleetController path stays byte-identical when
    # neither feature is armed
    fleet_cls = DisaggController if (roles or args.autoscale) \
        else FleetController
    fleet = fleet_cls(
        handles,
        heartbeat_ms=50.0 if args.heartbeat_ms is None
        else args.heartbeat_ms,
        suspect_misses=20, dead_misses=40, hedge_ms=args.hedge_ms,
        tracer=harness.fleet_tracer if harness is not None else None)
    if args.autoscale:
        scale_role = "decode" if roles else "unified"
        decode_n = roles[1] if roles else args.replicas
        spawn_seq = [len(replica_specs)]

        def _spawn():
            # cold spawn: a fresh engine on the shared params, warmed
            # over the same buckets (the warm-restart standby path is
            # preferred by the autoscaler and never reaches here).
            # Spawned replicas serve without a per-replica metrics
            # registry: the merged snapshot covers the starting fleet.
            idx = spawn_seq[0]
            spawn_seq[0] += 1
            eng = Engine(cfg, params, engine_cfg, seed=args.seed)
            eng.aot_compile(buckets)
            return EngineReplica(f"{'d' if roles else 'r'}{idx}", eng,
                                 role=scale_role)

        fleet.autoscaler = Autoscaler(
            fleet, role=scale_role,
            min_replicas=1 if args.min_replicas is None
            else args.min_replicas,
            max_replicas=decode_n if args.max_replicas is None
            else args.max_replicas,
            factory=_spawn)
    recorders = []
    fleet_flight = None
    if args.flight_recorder:
        from apex_tpu.serve.fleet import attach_fleet_recorders

        # one recorder per replica (PATH.rK: auto-dump scoped to THAT
        # replica's death/suspect transition, with its registry row)
        # plus the fleet-plane recorder, returned last — ONE wiring
        # shared with apex-tpu-bench
        recorders = attach_fleet_recorders(fleet, args.flight_recorder,
                                           harness)
        fleet_flight = recorders[-1]
    if args.drain_on == "SIGTERM":
        # stop admitting, shed the queued backlog retriable, finish
        # in-flight, exit cleanly — the rolling-deployment contract
        # (fleet.begin_drain is one flag write; safe at signal depth,
        # the control thread's next pump does the shedding)
        signal_mod.signal(signal_mod.SIGTERM,
                          lambda *_: fleet.begin_drain())
    for i, toks in enumerate(prompts):
        tenant = f"tenant-{i % args.tenants}" if args.tenants > 0 else None
        fleet.submit(Request(request_id=f"req-{i}", tokens=toks,
                             max_new_tokens=args.max_new_tokens,
                             eos_id=args.eos_id,
                             deadline_ms=args.deadline_ms,
                             tenant=tenant))
    try:
        import contextlib

        # liveness bound scaled to the workload: a large --requests run
        # is long, not wedged. A fatal control-loop exception leaves the
        # fleet-plane postmortem before propagating.
        with (fleet_flight.guard("fleet") if fleet_flight is not None
              else contextlib.nullcontext()):
            stats = fleet.run(max_wall_s=max(60.0, 2.0 * len(prompts)))
    finally:
        if exporter is not None:
            exporter.stop()
        if want_metrics and args.metrics_snapshot:
            # one mergeable snapshot PER replica (PATH.rK — what a real
            # fleet's ranks each write) plus the metrics_merge fleet
            # view at PATH itself, all atomic; provenance meta rides
            # each so the device-mismatch gate still sees it
            from apex_tpu.monitor.export import (atomic_write_json,
                                                 merge_snapshots)

            docs = []
            for i, h in enumerate(handles):
                doc = h.metrics.registry.snapshot(
                    meta={**(metrics_meta or {}),
                          "replica": h.replica_id})
                atomic_write_json(f"{args.metrics_snapshot}.r{i}", doc)
                docs.append(doc)
            atomic_write_json(args.metrics_snapshot,
                              merge_snapshots(docs))
        for fr in recorders:
            fr.detach()
        if harness is not None:
            # finalize PATH + every PATH.rK into strict JSON
            harness.close()
        if tel is not None:
            tel.close()
    for rec in stats.requests:
        print(json.dumps(rec, sort_keys=True))
    final = {"summary": stats.summary(),
             "decode_compiles": [h.engine.decode_traces
                                 for h in handles],
             "prefill_compiles": [h.engine.prefill_traces
                                  for h in handles]}
    if harness is not None:
        # sampling provenance: how many journeys streamed, how many the
        # tail capture promoted, how many happy-path ones were dropped
        final["trace"] = harness.stats()
    print(json.dumps(final, sort_keys=True))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="apex-tpu-serve",
        description="run a scripted or stdin token-id request stream "
                    "through the apex_tpu.serve engine")
    ap.add_argument("--config", default="tiny",
                    choices=["tiny", "small", "xl"],
                    help="GPT2Config preset (default tiny)")
    ap.add_argument("--dtype", default="fp32",
                    choices=["fp32", "bf16"],
                    help="compute dtype (fp32 default: bit-exact decode)")
    ap.add_argument("--num-slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=64,
                    help="per-slot context bound (prompt + generated)")
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--eos-id", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request latency budget from submit; expired "
                         "requests (queued or running) terminate with "
                         "finish_reason=deadline")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="bound the admission backlog; overflow is shed "
                         "per --shed-policy as a terminal, retriable "
                         "rejection (default: unbounded)")
    ap.add_argument("--shed-policy", default="reject-newest",
                    choices=["reject-newest", "shed-oldest", "priority"],
                    help="who pays when the queue is full")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="warm restarts to attempt after a fatal tick "
                         "exception (tick journal + recovery; 0 = fail "
                         "fast, the pre-PR-8 behavior)")
    ap.add_argument("--page-size", type=int, default=None,
                    help="tokens per KV page: enables the paged block "
                         "pool (must divide --max-len; the tuned decode "
                         "block_k must divide it). Default: per-slot "
                         "max_len reservation")
    ap.add_argument("--num-pages", type=int, default=None,
                    help="pool capacity in pages incl. the reserved null "
                         "page (default: same token capacity as the slot "
                         "cache; smaller overcommits — the point of "
                         "paging). Needs --page-size")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="share read-only prompt-prefix pages across "
                         "requests (hash-indexed, page-granular; needs "
                         "--page-size)")
    ap.add_argument("--requests", type=int, default=4,
                    help="scripted request count (ignored with --stdin)")
    ap.add_argument("--prompt-len", type=int, default=8,
                    help="scripted prompt length")
    ap.add_argument("--tenants", type=int, default=0,
                    help="label scripted requests round-robin across N "
                         "tenants (tenant-0..tenant-N-1) so the live "
                         "metrics carry a per-tenant breakdown "
                         "(0 = unlabeled, the 'default' tenant; "
                         "incompatible with --stdin)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve live Prometheus-text /metrics + JSON "
                         "/metrics.json from this port while the "
                         "scheduler runs (0 = ephemeral; the bound URL "
                         "prints to stderr)")
    ap.add_argument("--metrics-snapshot", default=None,
                    help="commit an atomic mergeable metrics snapshot "
                         "JSON here at exit — the per-rank artifact "
                         "tools/metrics_merge.py folds into a fleet view")
    ap.add_argument("--slo", action="append", default=None,
                    metavar="NAME=VALUE",
                    help="arm a live SLO objective (repeatable): "
                         "ttft_p99_ms=50 (threshold ms), "
                         "deadline_miss_frac=0.05 / shed_frac=0.1 "
                         "(error budgets); breaches publish "
                         "serve_slo_breach on the event bus")
    ap.add_argument("--slo-window", default=None, metavar="SHORT:LONG",
                    help="burn-rate window spans in seconds "
                         "(default 60:300)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="run N thread-backed engine replicas under the "
                         "fleet controller (heartbeat health, failover "
                         "re-dispatch, hedging; default 1 = the single "
                         "scheduler path)")
    ap.add_argument("--hedge-ms", type=float, default=None,
                    help="hedged dispatch: a request with no terminal "
                         "status after this many ms fires one copy on a "
                         "second replica; first terminal wins, the loser "
                         "is aborted (needs --replicas >= 2)")
    ap.add_argument("--heartbeat-ms", type=float, default=None,
                    help="replica heartbeat interval; a replica silent "
                         "for 20 intervals is suspect, 40 is dead and "
                         "its requests fail over (default 50 -> a 2s "
                         "death budget, sized so a contended decode "
                         "tick never reads as a death; needs "
                         "--replicas >= 2)")
    ap.add_argument("--drain-on", default=None, choices=["SIGTERM"],
                    help="on this signal, stop admitting new work, shed "
                         "still-queued requests as retriable "
                         "rejections, and finish in-flight ones before "
                         "exiting cleanly (needs --replicas >= 2)")
    ap.add_argument("--roles", default=None, metavar="P:D",
                    help="disaggregate the fleet: P dedicated prefill "
                         "replicas streaming certified KV pages into D "
                         "decode replicas (needs --page-size + "
                         "--prefix-cache; --replicas, if given, must "
                         "equal P+D)")
    ap.add_argument("--autoscale", action="store_true",
                    help="SLO-driven decode autoscaling: scale up on "
                         "burn rate / page pressure, rolling-drain down "
                         "when quiet (needs --slo and a fleet)")
    ap.add_argument("--min-replicas", type=int, default=None,
                    help="autoscaler floor for the scaled role "
                         "(default 1; needs --autoscale)")
    ap.add_argument("--max-replicas", type=int, default=None,
                    help="autoscaler ceiling for the scaled role "
                         "(default: the starting count; needs "
                         "--autoscale)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel mesh size: shard params + the "
                         "KV pool on the head axis over N devices and "
                         "run decode/prefill under shard_map (must "
                         "divide the model's n_head; default 1 = single "
                         "chip; docs/serving.md 'Tensor-parallel "
                         "decode')")
    ap.add_argument("--tp-sync", default="exact",
                    choices=["exact", "overlap", "relaxed"],
                    help="per-layer cross-rank sync with --tp >= 2: "
                         "exact (all-gather concatenation — "
                         "bit-identical to the single-chip engine, the "
                         "default), overlap (TokenWeave split psums "
                         "interleaved with norm/residual compute), "
                         "relaxed (ONE deferred all-reduce per layer; "
                         "opt-in approximation)")
    ap.add_argument("--spec-draft-len", type=int, default=None,
                    metavar="K",
                    help="speculative decoding: host n-gram drafter "
                         "proposes K tokens per active slot and one "
                         "compiled verify step (a K+1-position prefill "
                         "at decode width) scores them — exact "
                         "acceptance, greedy streams bit-identical to "
                         "the one-token engine (docs/serving.md "
                         "'Speculative decoding and the decode-policy "
                         "zoo')")
    ap.add_argument("--decode-policy", default=None, metavar="POLICY",
                    help="per-request sampling policy seam: greedy | "
                         "top_p[=P] | min_p[=M] | spec(POLICY), optional "
                         "',t=T' temperature suffix; policy knobs ride "
                         "the compiled calls as data, so mixing "
                         "policies in one batch never retraces "
                         "(beam-like policies are refused — no exact "
                         "per-token acceptance test exists)")
    ap.add_argument("--kv-quant", default=None,
                    choices=["int8", "mxfp8"],
                    help="block-scale KV-cache quantization "
                         "(apex_tpu.quant, docs/quantization.md): store "
                         "K/V as codec bytes with one fp32 scale per "
                         "(token, head); needs --dtype fp32 (the "
                         "quality gate's reference engine) and is "
                         "refused with --spec-draft-len (exact "
                         "acceptance oracle vs tolerance-gated cache)")
    ap.add_argument("--stdin", action="store_true",
                    help="read one token-id request per input line")
    ap.add_argument("--aot", action="store_true",
                    help="AOT-compile decode + the prompt bucket before "
                         "serving (startup pays the trace, not traffic)")
    ap.add_argument("--telemetry-jsonl", default=None,
                    help="mirror serve_* bus events into this JSONL")
    ap.add_argument("--trace-jsonl", default=None,
                    help="write per-request span traces (queue/prefill/"
                         "decode/complete) as Perfetto-loadable "
                         "Chrome-trace JSON; with --replicas N the "
                         "fleet journey lands here and each replica's "
                         "trace at PATH.rK (tools/trace_explain.py "
                         "merges + reconciles them)")
    ap.add_argument("--trace-sample", type=float, default=None,
                    metavar="RATE",
                    help="deterministic head sampling over request "
                         "journeys (seeded by --seed): only RATE of "
                         "happy-path journeys reach the trace file, "
                         "while every bad-outcome journey (deadline/"
                         "evict/reject/failover/hedge, or terminal "
                         "inside an SLO breach) is promoted in full — "
                         "the slow tail is always captured (needs "
                         "--trace-jsonl; default: trace everything)")
    ap.add_argument("--flight-recorder", default=None,
                    help="crash-time flight-recorder dump path: on "
                         "preemption, watchdog escalation, or a fatal "
                         "scheduler error, the last events + open spans "
                         "+ memory snapshot land here atomically")
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    from apex_tpu.models.gpt2 import GPT2Config
    from apex_tpu.serve.engine import (Engine, EngineConfig,
                                       init_gpt2_params)
    from apex_tpu.serve.scheduler import Request, ServeScheduler

    cfg = getattr(GPT2Config, args.config)()
    if args.dtype == "fp32":
        import dataclasses

        cfg = dataclasses.replace(cfg, compute_dtype=jnp.float32)
    max_len = min(args.max_len, cfg.n_positions)
    if max_len < args.max_len:
        print(f"apex-tpu-serve: --max-len {args.max_len} clamped to the "
              f"model's n_positions={max_len}", file=sys.stderr)

    # tensor-parallel flag matrix, BEFORE any params/compile work
    # (PR-10 precedent: inert/contradictory combinations are loud usage
    # errors, never silent no-ops)
    if args.tp < 1:
        print(f"apex-tpu-serve: --tp {args.tp} must be >= 1",
              file=sys.stderr)
        return 2
    if cfg.n_head % args.tp:
        print(f"apex-tpu-serve: --tp {args.tp} must divide the model's "
              f"n_head={cfg.n_head} (the serving mesh shards whole "
              f"heads)", file=sys.stderr)
        return 2
    if args.tp_sync != "exact" and args.tp == 1:
        print(f"apex-tpu-serve: --tp-sync {args.tp_sync} relaxes "
              f"cross-rank synchronization; it needs --tp >= 2 (a "
              f"single chip has no collectives to overlap or relax)",
              file=sys.stderr)
        return 2

    # speculative-decoding flag matrix, BEFORE any params/compile work
    # (same PR-10 precedent): a draft width that cannot draft and a
    # policy the acceptance oracle cannot verify are usage errors
    if args.spec_draft_len is not None and args.spec_draft_len < 1:
        print(f"apex-tpu-serve: --spec-draft-len {args.spec_draft_len} "
              f"must be >= 1 (it is the drafter's proposal width; omit "
              f"the flag for one-token decode)", file=sys.stderr)
        return 2
    spec_k = args.spec_draft_len or 0
    if args.decode_policy is not None:
        from apex_tpu.serve.spec import parse_policy
        try:
            parse_policy(args.decode_policy, spec_draft_len=spec_k)
        except ValueError as e:
            print(f"apex-tpu-serve: --decode-policy: {e}",
                  file=sys.stderr)
            return 2

    # KV-quantization flag matrix, BEFORE any params/compile work (same
    # PR-10 precedent; argparse choices already refuse unknown codecs)
    if args.kv_quant is not None:
        if args.dtype != "fp32":
            print(f"apex-tpu-serve: --kv-quant {args.kv_quant} needs "
                  f"--dtype fp32: the quantization quality gate is "
                  f"calibrated against the fp32 engine as the exact "
                  f"reference", file=sys.stderr)
            return 2
        if spec_k:
            print(f"apex-tpu-serve: --kv-quant {args.kv_quant} is "
                  f"incompatible with --spec-draft-len {spec_k}: the "
                  f"speculative acceptance oracle is bit-exact, the "
                  f"quantized cache is tolerance-gated (drop one)",
                  file=sys.stderr)
            return 2
        from apex_tpu.quant.kv import check_kv_codec
        try:
            check_kv_codec(args.kv_quant)
        except ValueError as e:
            print(f"apex-tpu-serve: --kv-quant: {e}", file=sys.stderr)
            return 2

    # disaggregation / autoscaler flag matrix, BEFORE any params or
    # compile work (PR-10 precedent: inert or contradictory combinations
    # are loud usage errors in milliseconds, never silent no-ops)
    roles = _parse_roles(args.roles)
    if args.roles is not None:
        if roles is None:
            print(f"apex-tpu-serve: --roles {args.roles!r}: want P:D "
                  f"positive integers (P prefill replicas, D decode "
                  f"replicas, e.g. 1:2)", file=sys.stderr)
            return 2
        if args.replicas is not None and args.replicas != sum(roles):
            print(f"apex-tpu-serve: --roles {args.roles} is a "
                  f"{sum(roles)}-replica fleet; --replicas "
                  f"{args.replicas} contradicts it (drop one)",
                  file=sys.stderr)
            return 2
        if not args.page_size or not args.prefix_cache:
            print("apex-tpu-serve: --roles streams prompt pages "
                  "through the prefix index; it needs --page-size and "
                  "--prefix-cache", file=sys.stderr)
            return 2
        args.replicas = sum(roles)
    elif args.replicas is None:
        args.replicas = 1
    if (args.min_replicas is not None or args.max_replicas is not None) \
            and not args.autoscale:
        print("apex-tpu-serve: --min-replicas/--max-replicas bound the "
              "autoscaler; they need --autoscale", file=sys.stderr)
        return 2
    if args.autoscale:
        if args.replicas < 2:
            print("apex-tpu-serve: --autoscale scales a FLEET; it needs "
                  "--replicas >= 2 (or --roles)", file=sys.stderr)
            return 2
        if not args.slo:
            print("apex-tpu-serve: --autoscale scales on SLO burn rate; "
                  "give it at least one --slo NAME=VALUE objective",
                  file=sys.stderr)
            return 2
        mn = 1 if args.min_replicas is None else args.min_replicas
        decode_n = roles[1] if roles else args.replicas
        mx = decode_n if args.max_replicas is None else args.max_replicas
        if not 1 <= mn <= mx:
            print(f"apex-tpu-serve: need 1 <= --min-replicas <= "
                  f"--max-replicas, got {mn} / {mx}", file=sys.stderr)
            return 2

    # fleet flag matrix, BEFORE any params/compile work: an inert or
    # contradictory combination is a usage error that must fail in
    # milliseconds (PR-10 precedent), never a silent no-op
    if args.replicas < 1:
        print(f"apex-tpu-serve: --replicas {args.replicas} must be >= 1",
              file=sys.stderr)
        return 2
    if args.replicas == 1:
        inert = [(args.hedge_ms is not None, "--hedge-ms"),
                 (args.heartbeat_ms is not None, "--heartbeat-ms"),
                 (args.drain_on is not None, "--drain-on")]
        bad = [flag for cond, flag in inert if cond]
        if bad:
            print(f"apex-tpu-serve: {bad[0]} is fleet routing; it needs "
                  f"--replicas >= 2 (one replica has nowhere to hedge, "
                  f"fail over, or drain to)", file=sys.stderr)
            return 2
    else:
        if args.heartbeat_ms is not None and args.heartbeat_ms <= 0:
            # `or 50.0` would silently replace an explicit 0 with the
            # default — the exact silent-no-op class this matrix exists
            # to refuse
            print(f"apex-tpu-serve: --heartbeat-ms "
                  f"{args.heartbeat_ms:g} must be > 0", file=sys.stderr)
            return 2
        # --trace-jsonl / --flight-recorder / --metrics-port are fleet
        # citizens since PR 13 (cross-replica journeys, per-replica
        # postmortems, the merged pull endpoint); only the warm-restart
        # supervisor still wires exactly ONE scheduler
        if args.max_restarts > 0:
            print(f"apex-tpu-serve: --max-restarts cannot apply with "
                  f"--replicas {args.replicas}: the per-replica "
                  f"warm-restart supervisor wires ONE scheduler; the "
                  f"fleet recovers by failover re-dispatch",
                  file=sys.stderr)
            return 2

    # trace sampling is a property OF the trace file: without
    # --trace-jsonl there is nothing to sample (and silently ignoring
    # the rate would leave the user believing tail capture is armed)
    if args.trace_sample is not None:
        if not args.trace_jsonl:
            print("apex-tpu-serve: --trace-sample needs --trace-jsonl "
                  "(it decides which journeys reach that file)",
                  file=sys.stderr)
            return 2
        if not 0.0 < args.trace_sample <= 1.0:
            print(f"apex-tpu-serve: --trace-sample {args.trace_sample:g} "
                  f"must be in (0, 1] (1 = trace everything)",
                  file=sys.stderr)
            return 2

    if args.tenants > 0 and args.stdin:
        # before the stdin read: stdin lines carry no tenant identity to
        # label — silently dropping the flag would leave every series
        # under "default" while the user believes the per-tenant
        # breakdown is armed
        print("apex-tpu-serve: --tenants labels the SCRIPTED workload; "
              "it cannot apply to --stdin requests", file=sys.stderr)
        return 2

    # validate the request stream BEFORE paying for params + compiles: a
    # malformed stdin line must fail in milliseconds, not after trace time
    if args.stdin:
        try:
            prompts = [p for p in (_parse_line(l) for l in sys.stdin)
                       if p]
        except ValueError as e:
            print(f"apex-tpu-serve: request lines must be whitespace- or "
                  f"comma-separated integer token ids ({e})",
                  file=sys.stderr)
            return 2
    else:
        rng = np.random.RandomState(args.seed)
        plen = max(1, min(args.prompt_len, max_len - 1))
        prompts = [[int(t) for t in rng.randint(0, cfg.vocab_size, plen)]
                   for _ in range(args.requests)]
    if not prompts:
        print("apex-tpu-serve: no requests", file=sys.stderr)
        return 2
    bad = [i for i, p in enumerate(prompts)
           if max(p) >= cfg.vocab_size or min(p) < 0]
    if bad:
        print(f"apex-tpu-serve: request {bad[0]} has token ids outside "
              f"vocab [0, {cfg.vocab_size})", file=sys.stderr)
        return 2
    long = [i for i, p in enumerate(prompts) if len(p) >= max_len]
    if long:
        print(f"apex-tpu-serve: request {long[0]} has "
              f"{len(prompts[long[0]])} tokens — no room to generate "
              f"under max_len={max_len}", file=sys.stderr)
        return 2

    # SLO specs are usage input: a typo'd objective must fail before the
    # engine pays for params + compiles
    slo = None
    if args.slo_window and not args.slo:
        # silently ignoring a window spec would leave the user believing
        # burn-rate tracking is configured — same usage-error contract as
        # every other inapplicable flag combination here
        print("apex-tpu-serve: --slo-window needs at least one --slo "
              "NAME=VALUE objective to apply to", file=sys.stderr)
        return 2
    if args.slo:
        from apex_tpu.monitor.slo import SLOTracker, parse_slo_specs

        slo_kw = {}
        if args.slo_window:
            short, _, long_ = args.slo_window.partition(":")
            try:
                slo_kw = {"short_window_s": float(short),
                          "long_window_s": float(long_)}
            except ValueError:
                print(f"apex-tpu-serve: --slo-window {args.slo_window!r}: "
                      f"want SHORT:LONG seconds (e.g. 30:150)",
                      file=sys.stderr)
                return 2
        try:
            slo = SLOTracker(parse_slo_specs(args.slo, **slo_kw))
        except ValueError as e:
            print(f"apex-tpu-serve: {e}", file=sys.stderr)
            return 2

    if args.replicas > 1:
        # every usage check above already ran: the fleet path pays for
        # params/compiles only once the request stream and SLO specs
        # are known-good
        return _run_fleet(args, cfg, max_len, prompts, slo)

    # live metrics: any of the three flags arms the per-tenant registry.
    # The pull endpoint binds BEFORE the engine pays for params +
    # compiles — an unbindable port is a usage error that must fail in
    # milliseconds with exit 2, not a raw traceback after trace time
    metrics = exporter = metrics_meta = None
    if (args.metrics_port is not None or args.metrics_snapshot
            or slo is not None):
        from apex_tpu.serve.metrics import ServeMetrics
        from apex_tpu.utils.env import capture_provenance

        metrics = ServeMetrics(slo=slo)
        # provenance rides the snapshot meta (same as apex-tpu-bench):
        # check_regression's device-mismatch guard reads it, so a
        # CPU-smoke serve snapshot can never silently gate real-chip
        # numbers
        metrics_meta = capture_provenance()
        if args.metrics_port is not None:
            from apex_tpu.monitor.export import MetricsExporter

            try:
                exporter = MetricsExporter(
                    metrics.registry, port=args.metrics_port,
                    snapshot_path=args.metrics_snapshot,
                    meta=metrics_meta).start()
            except OSError as e:
                print(f"apex-tpu-serve: cannot bind --metrics-port "
                      f"{args.metrics_port}: {e}", file=sys.stderr)
                return 2
            print(f"apex-tpu-serve: metrics at {exporter.url}",
                  file=sys.stderr)

    try:
        engine = Engine(
            cfg, init_gpt2_params(cfg, seed=args.seed),
            EngineConfig(num_slots=args.num_slots, max_len=max_len,
                         temperature=args.temperature, top_k=args.top_k,
                         page_size=args.page_size,
                         num_pages=args.num_pages,
                         prefix_cache=args.prefix_cache,
                         tp=args.tp, tp_sync=args.tp_sync,
                         spec_draft_len=args.spec_draft_len or 0,
                         decode_policy=args.decode_policy,
                         kv_quant=args.kv_quant),
            seed=args.seed)
    except ValueError as e:
        # bad pool geometry (page_size vs max_len/block_k, undersized
        # num_pages, prefix-cache without pages) and an undersized
        # device pool for --tp are usage errors, not crashes: the
        # engine's message says exactly what to fix
        print(f"apex-tpu-serve: {e}", file=sys.stderr)
        return 2

    # one Telemetry owns the whole observability lifecycle: event mirror
    # (--telemetry-jsonl), span tracer install/restore + Chrome-trace
    # export (--trace-jsonl) — same wiring as apex-tpu-bench. With
    # --trace-sample, the Chrome-trace export routes through the
    # tail-capture router instead (head sampling + bad-outcome
    # promotion); without it, today's stream-everything path is
    # untouched (rate=1 IS that behavior)
    tel = flight = mem = router = None
    if args.trace_jsonl and args.trace_sample is not None:
        from apex_tpu.monitor.trace import (ChromeTraceWriter,
                                            TailCaptureRouter, Tracer)

        tracer = Tracer()
        router = TailCaptureRouter(
            {"": ChromeTraceWriter(args.trace_jsonl, subscribe=False)},
            sample_rate=args.trace_sample, sample_seed=args.seed)
        if args.telemetry_jsonl:
            from apex_tpu.monitor import Telemetry

            tel = Telemetry(args.telemetry_jsonl)
    else:
        if args.telemetry_jsonl or args.trace_jsonl:
            from apex_tpu.monitor import Telemetry

            tel = Telemetry(args.telemetry_jsonl,
                            trace_jsonl=args.trace_jsonl)
        tracer = tel.tracer if tel is not None else None
    if args.trace_jsonl:
        from apex_tpu.monitor.memory import MemoryAccountant

        # sampled every 16 decode ticks: an allocator read per tick would
        # tax the decode hot path for a slowly-moving number
        mem = MemoryAccountant(every=16)
    if args.flight_recorder:
        from apex_tpu.monitor.flight import FlightRecorder

        flight = FlightRecorder(args.flight_recorder,
                                tracer=tracer).attach()

    if args.aot:
        # after the observability wiring: the AOT compiles publish their
        # static hbm_snapshot, which the sinks above must see
        engine.aot_compile([max(len(p) for p in prompts)])

    admission = journal = None
    if args.max_queue is not None:
        from apex_tpu.serve.resilience import AdmissionController

        admission = AdmissionController(max_queue=args.max_queue,
                                        shed_policy=args.shed_policy)
    if args.max_restarts > 0:
        from apex_tpu.serve.resilience import TickJournal

        journal = TickJournal()
    sched = ServeScheduler(engine, tracer=tracer, flight_recorder=flight,
                           memory_accountant=mem, admission=admission,
                           journal=journal, metrics=metrics)
    for i, toks in enumerate(prompts):
        # --tenants with --stdin already exited 2 above
        tenant = f"tenant-{i % args.tenants}" if args.tenants > 0 else None
        sched.submit(Request(request_id=f"req-{i}", tokens=toks,
                             max_new_tokens=args.max_new_tokens,
                             eos_id=args.eos_id,
                             deadline_ms=args.deadline_ms,
                             tenant=tenant))
    try:
        if journal is not None:
            from apex_tpu.serve.resilience import ServeSupervisor

            stats = ServeSupervisor(
                sched, max_restarts=args.max_restarts).run()
        else:
            stats = sched.run()
    finally:
        if exporter is not None:
            # stop() also commits the atomic snapshot file when
            # --metrics-snapshot rode along with the port
            exporter.stop()
        elif metrics is not None and args.metrics_snapshot:
            from apex_tpu.monitor.export import write_snapshot

            write_snapshot(metrics.registry, args.metrics_snapshot,
                           meta=metrics_meta)
        if args.metrics_snapshot and engine.tp > 1:
            # one mergeable snapshot PER TP RANK (PATH.tpK — the file a
            # real multi-host rank would write itself) plus the
            # metrics_merge fleet view at PATH.tp: the PR-10 seam used
            # for its designed purpose. The scheduler-level serving
            # registry above stays the per-request truth; the rank
            # files carry the shard-local view (local KV bytes, local
            # heads, collective traffic) that sums to the engine totals
            from apex_tpu.monitor.export import (atomic_write_json,
                                                 merge_snapshots)

            docs = engine.tp_rank_snapshots(meta=metrics_meta)
            for r, doc in enumerate(docs):
                atomic_write_json(f"{args.metrics_snapshot}.tp{r}", doc)
            atomic_write_json(f"{args.metrics_snapshot}.tp",
                              merge_snapshots(docs))
        if flight is not None:
            flight.detach()
        if router is not None:
            router.close()
        if tel is not None:
            tel.close()

    for rec in stats.requests:
        print(json.dumps(rec, sort_keys=True))
    final = {"summary": stats.summary(),
             "decode_compiles": engine.decode_traces,
             "prefill_compiles": engine.prefill_traces}
    if engine.tp > 1:
        # mesh provenance + the per-step collective contract: one
        # compile per MESH SHAPE is the invariant decode_compiles
        # witnesses above
        final["tp"] = {"tp": engine.tp, "sync": args.tp_sync,
                       "collectives_per_decode_step":
                           engine.tp_collectives_per_step()}
    if router is not None:
        final["trace"] = {"sample_rate": router.sampler.rate,
                          "sample_seed": router.sampler.seed,
                          **router.stats()}
    if metrics is not None:
        # live totals + SLO state ride the same final line the exact
        # summary does: the two views must reconcile (tier-1 asserts)
        final["metrics"] = metrics.summary()
    print(json.dumps(final, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
