"""FusedLayerNorm / FusedRMSNorm — TPU equivalent of
``apex/normalization/fused_layer_norm.py`` (module :724 / :841, functional
wrappers :670-721, CPU fallback :815-833, Mixed* variants :959-1031).

Public surface (functional, differentiable, jittable):
- ``fused_layer_norm_affine(x, weight, bias, normalized_shape, eps, memory_efficient)``
- ``fused_layer_norm(x, normalized_shape, eps, memory_efficient)``
- ``fused_rms_norm_affine(x, weight, normalized_shape, eps, memory_efficient)``
- ``fused_rms_norm(x, normalized_shape, eps, memory_efficient)``
- ``manual_rms_norm`` — pure-jnp reference (≈ fused_layer_norm.py:22)

plus flax modules ``FusedLayerNorm``, ``FusedRMSNorm``, ``MixedFusedLayerNorm``,
``MixedFusedRMSNorm``.

The hot path is the Pallas kernel pair in ops/pallas/layer_norm_kernel.py; a
pure-jnp path handles lane-unfriendly hidden sizes and serves as the parity
reference in tests (mirroring the reference's fallback to ``F.layer_norm``).
"""

from __future__ import annotations

import functools
import numbers
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.ops.pallas.layer_norm_kernel import ln_bwd_pallas, ln_fwd_pallas

_f32 = jnp.float32


def _norm_size(normalized_shape) -> int:
    if isinstance(normalized_shape, numbers.Integral):
        return int(normalized_shape)
    out = 1
    for d in normalized_shape:
        out *= int(d)
    return out


def _pallas_ok(hidden: int) -> bool:
    return hidden % 128 == 0 and hidden <= 65536


# ----------------------------------------------------------- jnp reference


def manual_layer_norm(x, weight, bias, normalized_shape, eps):
    h = _norm_size(normalized_shape)
    shape = x.shape
    x2 = x.reshape(-1, h).astype(_f32)
    mu = jnp.mean(x2, axis=1, keepdims=True)
    xc = x2 - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.reshape(1, h).astype(_f32)
    if bias is not None:
        y = y + bias.reshape(1, h).astype(_f32)
    return y.reshape(shape).astype(x.dtype)


def manual_rms_norm(x, weight, normalized_shape, eps):
    """Pure-jnp RMSNorm (ref fused_layer_norm.py:22 ``manual_rms_norm``)."""
    h = _norm_size(normalized_shape)
    shape = x.shape
    x2 = x.reshape(-1, h).astype(_f32)
    var = jnp.mean(x2 * x2, axis=1, keepdims=True)
    y = x2 * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.reshape(1, h).astype(_f32)
    return y.reshape(shape).astype(x.dtype)


# ------------------------------------------------------- pallas custom_vjp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused_norm(x, weight, bias, hidden: int, eps: float, rms: bool,
                affine: bool, memory_efficient: bool):
    y, _, _ = _fwd_impl(x, weight, bias, hidden, eps, rms, affine)
    return y.reshape(x.shape)


def _fwd_impl(x, weight, bias, hidden, eps, rms, affine):
    x2 = x.reshape(-1, hidden)
    return ln_fwd_pallas(x2, weight if affine else None,
                         bias if (affine and bias is not None) else None,
                         eps=eps, rms=rms)


def _fused_norm_fwd(x, weight, bias, hidden, eps, rms, affine,
                    memory_efficient):
    y2, mean, invvar = _fwd_impl(x, weight, bias, hidden, eps, rms, affine)
    if memory_efficient:
        # save output instead of input (fused_layer_norm.py:53-56)
        saved = y2
        res = (saved, weight, bias, mean if not rms else None, invvar, x.shape)
    else:
        res = (x.reshape(-1, hidden), weight, bias, mean if not rms else None,
               invvar, x.shape)
    return y2.reshape(x.shape), res


def _fused_norm_bwd(hidden, eps, rms, affine, memory_efficient, res, dy):
    saved2, weight, bias, mean, invvar, xshape = res
    dy2 = dy.reshape(-1, hidden)
    if mean is None:
        mean = jnp.zeros_like(invvar)
    dx2, dgamma, dbeta = ln_bwd_pallas(
        dy2, saved2, weight if affine else None,
        bias if (affine and bias is not None) else None, mean, invvar,
        rms=rms, memory_efficient=memory_efficient)
    dx = dx2.reshape(xshape)
    dw = dgamma.astype(weight.dtype).reshape(weight.shape) if affine else None
    db = (dbeta.astype(bias.dtype).reshape(bias.shape)
          if (affine and bias is not None) else None)
    return dx, dw, db


_fused_norm.defvjp(_fused_norm_fwd, _fused_norm_bwd)


# ------------------------------------------------------------- public API


def fused_layer_norm_affine(x, weight, bias, normalized_shape,
                            eps: float = 1e-5, memory_efficient: bool = False):
    """≈ apex fused_layer_norm_affine (fused_layer_norm.py:670)."""
    h = _norm_size(normalized_shape)
    if not _pallas_ok(h):
        return manual_layer_norm(x, weight, bias, normalized_shape, eps)
    return _fused_norm(x, weight, bias, h, eps, False, True, memory_efficient)


def fused_layer_norm(x, normalized_shape, eps: float = 1e-5,
                     memory_efficient: bool = False):
    """≈ apex fused_layer_norm (no affine)."""
    h = _norm_size(normalized_shape)
    if not _pallas_ok(h):
        return manual_layer_norm(x, None, None, normalized_shape, eps)
    return _fused_norm(x, None, None, h, eps, False, False, memory_efficient)


def fused_rms_norm_affine(x, weight, normalized_shape, eps: float = 1e-5,
                          memory_efficient: bool = False):
    """≈ apex fused_rms_norm_affine (fused_layer_norm.py:695)."""
    h = _norm_size(normalized_shape)
    if not _pallas_ok(h):
        return manual_rms_norm(x, weight, normalized_shape, eps)
    return _fused_norm(x, weight, None, h, eps, True, True, memory_efficient)


def fused_rms_norm(x, normalized_shape, eps: float = 1e-5,
                   memory_efficient: bool = False):
    h = _norm_size(normalized_shape)
    if not _pallas_ok(h):
        return manual_rms_norm(x, None, normalized_shape, eps)
    return _fused_norm(x, None, None, h, eps, True, False, memory_efficient)


# ------------------------------------------------------------ flax modules


class FusedLayerNorm(nn.Module):
    """flax module ≈ apex.normalization.FusedLayerNorm (fused_layer_norm.py:724)."""

    normalized_shape: int | Sequence[int]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = _norm_size(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, (h,),
                                self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, (h,),
                              self.param_dtype)
            return fused_layer_norm_affine(
                x, weight, bias, h, self.eps, self.memory_efficient)
        return fused_layer_norm(x, h, self.eps, self.memory_efficient)


class FusedRMSNorm(nn.Module):
    """flax module ≈ apex.normalization.FusedRMSNorm (fused_layer_norm.py:841)."""

    normalized_shape: int | Sequence[int]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = _norm_size(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, (h,),
                                self.param_dtype)
            return fused_rms_norm_affine(
                x, weight, h, self.eps, self.memory_efficient)
        return fused_rms_norm(x, h, self.eps, self.memory_efficient)


class MixedFusedLayerNorm(FusedLayerNorm):
    """Params kept in the IO dtype (≈ MixedFusedLayerNorm :959-1031)."""

    param_dtype: jnp.dtype = jnp.bfloat16


class MixedFusedRMSNorm(FusedRMSNorm):
    param_dtype: jnp.dtype = jnp.bfloat16
