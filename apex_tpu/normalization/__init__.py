"""Normalization — parity with ``apex/normalization/__init__.py``."""

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
    manual_layer_norm,
    manual_rms_norm,
)
