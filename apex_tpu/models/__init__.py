"""Model zoo used by the benchmark/integration configs (BASELINE.md):
ResNet-50 (configs 2-3), BERT-large (config 4), GPT-2 (config 5)."""
