"""ResNet family (flax, NHWC) — the imagenet benchmark model of the reference
(examples/imagenet/main_amp.py recipe; BASELINE.md configs 2-3: ResNet-50 +
FusedAdam single chip, + DDP/SyncBN on a v5e-8 mesh).

TPU-first choices: NHWC layout (XLA's native conv layout on TPU), bf16 compute
with fp32 norm statistics, SyncBatchNorm from apex_tpu.parallel as the norm
layer (axis_name=None degrades to plain BN for single-chip runs). The
bottleneck block mirrors torchvision semantics (the reference's
contrib.bottleneck accelerates the same block with cuDNN fusions — on TPU the
conv+BN+ReLU chains fuse in XLA).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batch_norm import SyncBatchNorm


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck with residual (expansion 4)."""

    features: int
    strides: int = 1
    axis_name: Optional[str] = None
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, use_running_average=False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       param_dtype=jnp.float32)
        bn = partial(SyncBatchNorm, axis_name=self.axis_name,
                     channel_axis=-1)
        needs_proj = (x.shape[-1] != self.features * 4 or self.strides != 1)
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = bn(self.features, name="bn1", fuse_relu=True)(
            y, use_running_average)
        y = conv(self.features, (3, 3), strides=(self.strides,) * 2,
                 padding=[(1, 1), (1, 1)], name="conv2")(y)
        y = bn(self.features, name="bn2", fuse_relu=True)(
            y, use_running_average)
        y = conv(self.features * 4, (1, 1), name="conv3")(y)
        y = bn(self.features * 4, name="bn3")(y, use_running_average)
        if needs_proj:
            residual = conv(self.features * 4, (1, 1),
                            strides=(self.strides,) * 2,
                            name="downsample_conv")(x)
            residual = bn(self.features * 4, name="downsample_bn")(
                residual, use_running_average)
        return jnp.maximum(y + residual.astype(y.dtype), 0.0)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    axis_name: Optional[str] = None
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        x = x.astype(self.compute_dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.compute_dtype,
                    param_dtype=jnp.float32, name="conv1")(x)
        x = SyncBatchNorm(64, axis_name=self.axis_name, fuse_relu=True,
                          name="bn1")(x, use_running_average)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        features = 64
        for stage, n_blocks in enumerate(self.stage_sizes):
            for blk in range(n_blocks):
                strides = 2 if (stage > 0 and blk == 0) else 1
                x = Bottleneck(features, strides, self.axis_name,
                               self.compute_dtype,
                               name=f"stage{stage}_block{blk}")(
                    x, use_running_average)
            features *= 2
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name="fc")(x)
        return x


def ResNet50(num_classes: int = 1000, axis_name: Optional[str] = None,
             compute_dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet([3, 4, 6, 3], num_classes, axis_name, compute_dtype)


def ResNet18ish(num_classes: int = 10, axis_name: Optional[str] = None,
                compute_dtype: Any = jnp.bfloat16) -> ResNet:
    """Small stand-in for fast tests (bottleneck blocks, [1,1,1,1] stages)."""
    return ResNet([1, 1, 1, 1], num_classes, axis_name, compute_dtype)
