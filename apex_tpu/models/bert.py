"""BERT family — benchmark config 4 (BASELINE.md: BERT-large pretrain +
FusedLAMB + FusedRMSNorm + contrib.xentropy on a v5e-16 mesh).

Encoder built from the framework's fused components: Pallas flash attention
(bidirectional), FusedRMSNorm (config 4 pairs BERT with the RMSNorm kernel),
dense_gelu_dense MLP, fused xentropy MLM loss. bf16 compute, fp32 params.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.normalization.fused_layer_norm import FusedRMSNorm
from apex_tpu.ops.pallas.flash_attention import flash_attention
from apex_tpu.transformer.fused_dense import dense_gelu_dense


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_position_embeddings: int = 512
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    type_vocab_size: int = 2
    compute_dtype: Any = jnp.bfloat16

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, max_position_embeddings=128,
                   hidden_size=128, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=512)

    @classmethod
    def large(cls):
        return cls()


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attn_mask: Optional[jax.Array] = None):
        c = self.cfg
        e = c.hidden_size
        h = c.num_attention_heads
        d = e // h
        b, s, _ = x.shape

        qkv = nn.Dense(3 * e, dtype=c.compute_dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        mask = None
        if attn_mask is not None:
            # attn_mask: (b, s) 1=valid → kernel mask (True=masked); the
            # flash kernel streams it blockwise without materializing s²
            mask = (attn_mask == 0)[:, None, None, :]
        o = flash_attention(q, k, v, False, mask=mask)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
        x = FusedRMSNorm(e, name="attn_norm")(
            x + nn.Dense(e, dtype=c.compute_dtype, name="attn_out")(o))

        w1 = self.param("mlp_fc_w", nn.initializers.normal(0.02),
                        (c.intermediate_size, e), jnp.float32)
        b1 = self.param("mlp_fc_b", nn.initializers.zeros,
                        (c.intermediate_size,), jnp.float32)
        w2 = self.param("mlp_proj_w", nn.initializers.normal(0.02),
                        (e, c.intermediate_size), jnp.float32)
        b2 = self.param("mlp_proj_b", nn.initializers.zeros, (e,),
                        jnp.float32)
        mlp = dense_gelu_dense(x, w1.astype(c.compute_dtype),
                               b1.astype(c.compute_dtype),
                               w2.astype(c.compute_dtype),
                               b2.astype(c.compute_dtype))
        return FusedRMSNorm(e, name="mlp_norm")(x + mlp)


class Bert(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attn_mask=None):
        c = self.cfg
        b, s = input_ids.shape
        wte = self.param("word_embeddings", nn.initializers.normal(0.02),
                         (c.vocab_size, c.hidden_size), jnp.float32)
        wpe = self.param("position_embeddings", nn.initializers.normal(0.02),
                         (c.max_position_embeddings, c.hidden_size),
                         jnp.float32)
        tte = self.param("token_type_embeddings",
                         nn.initializers.normal(0.02),
                         (c.type_vocab_size, c.hidden_size), jnp.float32)
        x = wte[input_ids] + wpe[:s][None]
        if token_type_ids is not None:
            x = x + tte[token_type_ids]
        x = FusedRMSNorm(c.hidden_size, name="emb_norm")(
            x.astype(c.compute_dtype))
        for i in range(c.num_hidden_layers):
            x = BertLayer(c, name=f"layer_{i}")(x, attn_mask)
        logits = jax.lax.dot_general(
            x, wte.astype(c.compute_dtype), (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return logits


def mlm_loss(model: Bert, params, input_ids, labels, ignore_index=-1):
    """Masked-LM pretrain loss via the fused xentropy: ``padding_idx``
    zeroes ignored positions inside the fused op; the mean is over the
    non-ignored count."""
    logits = model.apply(params, input_ids)
    loss = softmax_cross_entropy_loss(logits, labels,
                                      padding_idx=ignore_index)
    n = jnp.maximum(jnp.sum(labels != ignore_index), 1)
    return jnp.sum(loss) / n
