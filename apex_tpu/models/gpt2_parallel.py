"""Manually-parallel GPT-2 — the multi-chip training step: DP × TP × SP over a
``jax.sharding.Mesh`` via ``shard_map``.

Composition (the 'How to Scale Your Model' recipe, hand-annotated):
- **DP**: batch sharded over ``dp``; grads of every param psum over dp
  (the bucketed-psum DDP capability, apex_tpu.parallel.ddp).
- **TP**: Megatron column/row parallel linears over ``tp`` — q/k/v projections
  column-sharded (heads split), attention output row-sharded with a psum;
  MLP fc column-sharded, proj row-sharded with a psum. The wgrad-accum
  primitive semantics (fp32 grads for low-precision params) ride on
  preferred_element_type.
- **SP**: sequence sharded over ``sp``; attention runs the ring
  (apex_tpu.parallel.ring_attention) so K/V shards rotate over ICI while Q
  stays resident; positional embeddings sharded with the sequence.

Round 2 composes the remaining two axes (VERDICT item 5):
- **PP**: ``make_train_step_pp`` runs the block stack through the 1F1B
  pipeline (apex_tpu.parallel.pipeline.pipeline_train_1f1b) over the ``pp``
  axis — blocks stacked with a leading layer dim sharded over pp, embeddings
  and final-LN shared (replicated over pp, grads psum'd), the last stage
  computing the loss so cotangents enter the reverse pipeline on-device.
- **EP**: ``moe_experts > 0`` replaces the dense FFN with the
  expert-parallel MoE FFN (apex_tpu.parallel.moe.moe_ffn_ep) over the ``ep``
  axis, expert weights sharded (pp, ep, ...).

All five axes compose in one mesh (dp, pp, tp, sp, ep); degenerate (size-1)
axes cost nothing, so one train step covers every combination.

All params/optimizer state live in fp32; compute in bf16 (amp O1 shape);
optimizer is the fused Adam tree update (optimizers/functional.py).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from apex_tpu.utils.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.normalization.fused_layer_norm import (fused_layer_norm_affine)
from apex_tpu.optimizers.functional import adam_update
from apex_tpu.parallel.ring_attention import ring_self_attention
from apex_tpu.parallel.ulysses import ulysses_self_attention

_f32 = jnp.float32


def choose_mesh_shape(n: int) -> Tuple[int, int, int]:
    """Factor n devices into (dp, tp, sp), preferring dp ≥ tp ≥ sp."""
    dp = tp = sp = 1
    for axis in ("dp", "tp", "sp", "dp", "tp", "sp"):
        if n % 2 != 0 or n == 1:
            break
        n //= 2
        if axis == "dp":
            dp *= 2
        elif axis == "tp":
            tp *= 2
        else:
            sp *= 2
    dp *= n  # leftover odd factor onto dp
    return dp, tp, sp


def init_params(cfg: GPT2Config, key) -> Dict[str, Any]:
    """Full (unsharded) param dict; shard_map slices per the specs below."""
    ks = jax.random.split(key, 4 + cfg.n_layer)
    e = cfg.n_embd
    p = {
        "wte": jax.random.normal(ks[0], (cfg.vocab_size, e), _f32) * 0.02,
        "wpe": jax.random.normal(ks[1], (cfg.n_positions, e), _f32) * 0.01,
        "lnf_w": jnp.ones((e,), _f32),
        "lnf_b": jnp.zeros((e,), _f32),
        "blocks": [],
    }
    for i in range(cfg.n_layer):
        bk = jax.random.split(ks[4 + i], 6)
        std = 0.02
        p["blocks"].append({
            "ln1_w": jnp.ones((e,), _f32), "ln1_b": jnp.zeros((e,), _f32),
            "wq": jax.random.normal(bk[0], (e, e), _f32) * std,
            "wk": jax.random.normal(bk[1], (e, e), _f32) * std,
            "wv": jax.random.normal(bk[2], (e, e), _f32) * std,
            "wo": jax.random.normal(bk[3], (e, e), _f32) * std
                  / math.sqrt(2 * cfg.n_layer),
            "ln2_w": jnp.ones((e,), _f32), "ln2_b": jnp.zeros((e,), _f32),
            "fc_w": jax.random.normal(bk[4], (e, 4 * e), _f32) * std,
            "fc_b": jnp.zeros((4 * e,), _f32),
            "proj_w": jax.random.normal(bk[5], (4 * e, e), _f32) * std
                      / math.sqrt(2 * cfg.n_layer),
            "proj_b": jnp.zeros((e,), _f32),
        })
    return p


def param_specs(cfg: GPT2Config) -> Dict[str, Any]:
    """PartitionSpecs: TP-sharded projections, SP-sharded positions."""
    col = P(None, "tp")   # column parallel (output dim sharded)
    row = P("tp", None)   # row parallel (input dim sharded)
    rep = P()
    block = {
        "ln1_w": rep, "ln1_b": rep,
        "wq": col, "wk": col, "wv": col, "wo": row,
        "ln2_w": rep, "ln2_b": rep,
        "fc_w": col, "fc_b": P("tp"), "proj_w": row, "proj_b": rep,
    }
    return {
        "wte": rep,
        "wpe": P("sp", None),
        "lnf_w": rep, "lnf_b": rep,
        "blocks": [dict(block) for _ in range(cfg.n_layer)],
    }


def _grad_sync_specs(cfg: GPT2Config) -> Dict[str, Any]:
    """Axes each param's grad must be psum'd over = axes it is replicated on.
    Encoded as '|'-joined strings so the spec tree has leaf-for-leaf structure
    with the grad tree."""
    tp_sharded = "dp|sp"          # grads of tp-sharded params
    replicated = "dp|sp|tp"
    block = {
        "ln1_w": replicated, "ln1_b": replicated,
        "wq": tp_sharded, "wk": tp_sharded, "wv": tp_sharded,
        "wo": tp_sharded,
        "ln2_w": replicated, "ln2_b": replicated,
        "fc_w": tp_sharded, "fc_b": tp_sharded, "proj_w": tp_sharded,
        "proj_b": replicated,
    }
    return {
        "wte": replicated,
        "wpe": "dp|tp",           # sp-sharded: sum over dp and tp only
        "lnf_w": replicated, "lnf_b": replicated,
        "blocks": [dict(block) for _ in range(cfg.n_layer)],
    }


def _block_apply(cfg: GPT2Config, blk, x, sp_strategy: str = "ring"):
    """One transformer block on a local activation shard (b, s_local, e).

    TP: column-parallel q/k/v + row-parallel output with psum over tp;
    SP: sequence parallelism over sp — ``sp_strategy="ring"`` rotates K/V
    around the ICI ring (any head count), ``"ulysses"`` re-shards
    head↔sequence with two all-to-alls (needs local heads divisible by sp;
    see parallel/ulysses.py for the trade-off); EP: when the block carries
    expert weights ("gate_w"/"w1"/"w2"), the FFN is the expert-parallel MoE
    over ep.
    """
    cd = cfg.compute_dtype
    e = cfg.n_embd
    tp = axis_size("tp")
    h_local = cfg.n_head // tp
    d = e // cfg.n_head
    b, s_local, _ = x.shape

    y = fused_layer_norm_affine(x, blk["ln1_w"], blk["ln1_b"], e)
    q = (y @ blk["wq"].astype(cd))
    k = (y @ blk["wk"].astype(cd))
    v = (y @ blk["wv"].astype(cd))

    def heads(t):
        return t.reshape(b, s_local, h_local, d).transpose(0, 2, 1, 3)

    if sp_strategy == "ulysses":
        o = ulysses_self_attention(heads(q), heads(k), heads(v), "sp",
                                   causal=True)
    else:
        o = ring_self_attention(heads(q), heads(k), heads(v), "sp",
                                causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(b, s_local, h_local * d)
    # row-parallel output projection: partial matmul + psum over tp
    attn = jax.lax.psum(o @ blk["wo"].astype(cd), "tp")
    x = x + attn

    y = fused_layer_norm_affine(x, blk["ln2_w"], blk["ln2_b"], e)
    if "gate_w" in blk:
        # expert-parallel MoE FFN over ep (parallel/moe.py)
        from apex_tpu.parallel.moe import moe_ffn_ep

        y2 = y.reshape(b * s_local, e).astype(jnp.float32)
        mlp = moe_ffn_ep(y2, blk["gate_w"], blk["w1"], blk["w2"], "ep")
        x = x + mlp.reshape(b, s_local, e).astype(x.dtype)
    else:
        hmid = jax.nn.gelu(y @ blk["fc_w"].astype(cd)
                           + blk["fc_b"].astype(cd), approximate=False)
        mlp = jax.lax.psum(hmid @ blk["proj_w"].astype(cd), "tp")
        x = x + (mlp + blk["proj_b"].astype(cd))
    return x


def _forward_local(cfg: GPT2Config, params, tokens, targets, mask,
                   sp_strategy: str = "ring"):
    """Per-shard forward: tokens (b_local, s_local) on a (dp, tp, sp) mesh."""
    cd = cfg.compute_dtype
    e = cfg.n_embd
    tp = axis_size("tp")
    h_local = cfg.n_head // tp
    d = e // cfg.n_head

    # wpe is sp-sharded over positions; the parallel path trains at full
    # context length (seq == n_positions) so position shards align with
    # sequence shards
    sp = axis_size("sp")
    assert tokens.shape[1] * sp == cfg.n_positions, (
        f"parallel GPT-2 requires seq == n_positions "
        f"({tokens.shape[1]}*{sp} != {cfg.n_positions})")
    x = params["wte"][tokens].astype(cd) + params["wpe"][None].astype(cd)
    b, s_local, _ = x.shape

    for blk in params["blocks"]:
        x = _block_apply(cfg, blk, x, sp_strategy)

    x = fused_layer_norm_affine(x, params["lnf_w"], params["lnf_b"], e)
    logits = jax.lax.dot_general(x, params["wte"].astype(cd),
                                 (((2,), (1,)), ((), ())),
                                 preferred_element_type=_f32)
    loss_tok = softmax_cross_entropy_loss(logits, targets)
    # global masked mean over the dp × sp data shards
    tot = jax.lax.psum(jax.lax.psum(jnp.sum(loss_tok * mask), "dp"), "sp")
    cnt = jax.lax.psum(jax.lax.psum(jnp.sum(mask), "dp"), "sp")
    return tot / jnp.maximum(cnt, 1.0)


def make_train_step(cfg: GPT2Config, mesh: Mesh, lr: float = 1e-4,
                    sp_strategy: str = "ring"):
    """Returns jitted train_step(params, opt_state, tokens, targets, mask, step)
    → (params, opt_state, loss). Inputs are FULL arrays; sharding via specs.
    ``sp_strategy``: "ring" or "ulysses" (see _block_apply)."""
    if sp_strategy not in ("ring", "ulysses"):
        raise ValueError(
            f"sp_strategy must be 'ring' or 'ulysses', got {sp_strategy!r}")
    pspecs = param_specs(cfg)
    sync_axes = _grad_sync_specs(cfg)

    def local_step(params, m, v, tokens, targets, mask, step):
        def loss_fn(p):
            return _forward_local(cfg, p, tokens, targets, mask,
                                  sp_strategy)

        loss, grads = jax.value_and_grad(loss_fn)(params)

        # gradient sync: psum over every axis the param is replicated on.
        # With check_vma=False shard_map does not track replication, so the
        # replicated loss seeds a cotangent on EVERY device and each psum
        # transpose re-broadcasts it — after the sync psums the result is
        # exactly (dp·tp·sp)× the true gradient, for every param class
        # (verified empirically across (2,1,1)...(8,1,1),(1,8,1),(4,2,1),
        # (1,2,4) meshes). Normalize by the total mesh size.
        n_total = (axis_size("dp") * axis_size("tp")
                   * axis_size("sp"))

        def sync(g, axes):
            for ax in axes.split("|"):
                g = jax.lax.psum(g, ax)
            return g / n_total

        grads = jax.tree_util.tree_map(sync, grads, sync_axes)

        params, m, v = adam_update(params, grads, m, v, step=step, lr=lr,
                                   weight_decay=0.01)
        return params, m, v, loss

    state_specs = pspecs  # optimizer state sharded exactly like its params

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(pspecs, state_specs, state_specs,
                  P("dp", "sp"), P("dp", "sp"), P("dp", "sp"), P()),
        out_specs=(pspecs, state_specs, state_specs, P()),
        check_vma=False)

    @jax.jit
    def train_step(params, opt_state, tokens, targets, mask, step):
        m, v = opt_state
        params, m, v, loss = sharded(params, m, v, tokens, targets, mask,
                                     step)
        return params, (m, v), loss

    return train_step


def init_opt_state(params):
    z = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, _f32), params)
    z2 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, _f32), params)
    return (z, z2)


# ------------------------------------------------------------ pp/ep (round 2)


def init_params_pp(cfg: GPT2Config, key, moe_experts: int = 0):
    """Params for the pipelined model: blocks STACKED (leading n_layer dim,
    sharded over pp), embeddings/final-LN shared. ``moe_experts > 0`` builds
    expert-parallel FFNs (gate + per-expert w1/w2) instead of dense fc/proj."""
    p = init_params(cfg, key)
    blocks = p.pop("blocks")
    if moe_experts:
        e = cfg.n_embd
        ks = jax.random.split(jax.random.fold_in(key, 17),
                              3 * cfg.n_layer)
        for i, blk in enumerate(blocks):
            for k_ in ("fc_w", "fc_b", "proj_w", "proj_b"):
                del blk[k_]
            std = 0.02
            blk["gate_w"] = jax.random.normal(
                ks[3 * i], (e, moe_experts), _f32) * std
            blk["w1"] = jax.random.normal(
                ks[3 * i + 1], (moe_experts, e, 4 * e), _f32) * std
            blk["w2"] = jax.random.normal(
                ks[3 * i + 2], (moe_experts, 4 * e, e), _f32) * std \
                / math.sqrt(2 * cfg.n_layer)
    stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *blocks)
    shared = {"wte": p["wte"], "wpe": p["wpe"],
              "lnf_w": p["lnf_w"], "lnf_b": p["lnf_b"]}
    return {"blocks": stacked, "shared": shared}


def param_specs_pp(cfg: GPT2Config, moe_experts: int = 0):
    """PartitionSpecs for the pipelined layout: leading layer dim over pp,
    TP/EP dims inside, shared params replicated over pp."""
    col = P("pp", None, "tp")
    row = P("pp", "tp", None)
    rep = P("pp")
    block = {
        "ln1_w": rep, "ln1_b": rep,
        "wq": col, "wk": col, "wv": col, "wo": row,
        "ln2_w": rep, "ln2_b": rep,
    }
    if moe_experts:
        block.update({
            "gate_w": P("pp", None, None),
            "w1": P("pp", "ep", None, None),
            "w2": P("pp", "ep", None, None),
        })
    else:
        block.update({
            "fc_w": col, "fc_b": P("pp", "tp"),
            "proj_w": row, "proj_b": rep,
        })
    shared = {"wte": P(), "wpe": P("sp", None), "lnf_w": P(), "lnf_b": P()}
    return {"blocks": block, "shared": shared}


def _grad_sync_specs_pp(cfg: GPT2Config, moe_experts: int = 0):
    """Axes (|-joined) each grad must be psum'd over in the pp layout.
    Blocks are pp-sharded so never synced over pp; the pipeline already
    psums shared grads over pp internally."""
    tp_sharded = "dp|sp|ep" if moe_experts else "dp|sp"
    replicated = "dp|sp|tp|ep" if moe_experts else "dp|sp|tp"
    block = {
        "ln1_w": replicated, "ln1_b": replicated,
        "wq": tp_sharded, "wk": tp_sharded, "wv": tp_sharded,
        "wo": tp_sharded,
        "ln2_w": replicated, "ln2_b": replicated,
    }
    if moe_experts:
        block.update({"gate_w": replicated,
                      "w1": "dp|sp|tp", "w2": "dp|sp|tp"})
    else:
        block.update({"fc_w": tp_sharded, "fc_b": tp_sharded,
                      "proj_w": tp_sharded, "proj_b": replicated})
    shared = {"wte": replicated, "wpe": "dp|tp|ep" if moe_experts
              else "dp|tp", "lnf_w": replicated, "lnf_b": replicated}
    return {"blocks": block, "shared": shared}


def make_train_step_pp(cfg: GPT2Config, mesh: Mesh, lr: float = 1e-4,
                       num_microbatches: int = 4, moe_experts: int = 0):
    """Composed 5-axis (dp, pp, tp, sp, ep) train step: 1F1B pipeline over
    pp wrapping the dp×tp×sp(×ep) block stack. Returns jitted
    train_step(params, opt_state, tokens, targets, mask, step) →
    (params, opt_state, loss)."""
    from apex_tpu.parallel.pipeline import pipeline_train_1f1b

    pspecs = param_specs_pp(cfg, moe_experts)
    sync_axes = _grad_sync_specs_pp(cfg, moe_experts)
    pp = mesh.shape["pp"]
    assert cfg.n_layer % pp == 0, \
        "pp (pipeline stages) must divide n_layer evenly"
    cd = cfg.compute_dtype
    e = cfg.n_embd
    M = num_microbatches

    def local_step(blocks, shared, m, v, tokens, targets, mask, step):
        b_local, s_local = tokens.shape
        assert b_local % M == 0, "num_microbatches must divide local batch"
        mb = b_local // M
        micro = tuple(a.reshape(M, mb, s_local)
                      for a in (tokens, targets, mask))
        x_template = jnp.zeros((mb, s_local, e), cd)
        # GLOBAL valid-token count (all microbatches): per-microbatch losses
        # are tot_i / cnt_total so their sum is the exact global token mean
        # (per-microbatch normalization would overweight sparse microbatches)
        cnt_total = jnp.maximum(jax.lax.psum(jax.lax.psum(
            jnp.sum(mask), "dp"), "sp"), 1.0)

        def stage_fn(stage_blocks, shared_, x_act, tok, tgt, msk):
            my_pp = jax.lax.axis_index("pp")
            last = my_pp == axis_size("pp") - 1
            # cond (not where): only stage 0 pays the (vocab, e) embedding
            # gather — and its scatter-add cotangent — per tick; mirrors the
            # lax.cond gating of the vocab-logits loss on the last stage
            x = jax.lax.cond(
                my_pp == 0,
                lambda: (shared_["wte"][tok].astype(cd)
                         + shared_["wpe"][None].astype(cd)),
                lambda: x_act)
            lps = cfg.n_layer // pp
            for i in range(lps):
                blk = jax.tree_util.tree_map(lambda l: l[i], stage_blocks)
                x = _block_apply(cfg, blk, x)

            def loss_of(xv):
                y = fused_layer_norm_affine(xv, shared_["lnf_w"],
                                            shared_["lnf_b"], e)
                logits = jax.lax.dot_general(
                    y, shared_["wte"].astype(cd), (((2,), (1,)), ((), ())),
                    preferred_element_type=_f32)
                loss_tok = softmax_cross_entropy_loss(logits, tgt)
                tot = jax.lax.psum(jax.lax.psum(
                    jnp.sum(loss_tok * msk), "dp"), "sp")
                return tot / cnt_total

            # only the last stage pays the vocab matmul (lax.cond: 1 branch)
            loss_i = jax.lax.cond(last, loss_of,
                                  lambda _: jnp.float32(0.0), x)
            return x, loss_i

        loss_sum, g_blocks, g_shared = pipeline_train_1f1b(
            stage_fn, blocks, shared, x_template, micro, M, "pp")
        loss = loss_sum  # already the global token mean (see cnt_total)

        # grad sync + replication-factor normalization (see make_train_step:
        # with check_vma=False each sync psum re-broadcasts the seed
        # cotangent, giving n_total× the true grad; pp is handled inside the
        # pipeline for shared params and absent for block params)
        n_total = (axis_size("dp") * axis_size("tp")
                   * axis_size("sp") * axis_size("ep"))

        def sync(g, axes):
            for ax in axes.split("|"):
                g = jax.lax.psum(g, ax)
            return g / n_total

        g_blocks = {k_: sync(g_blocks[k_], sync_axes["blocks"][k_])
                    for k_ in g_blocks}
        g_shared = {k_: sync(g_shared[k_], sync_axes["shared"][k_])
                    for k_ in g_shared}

        params = {"blocks": blocks, "shared": shared}
        grads = {"blocks": g_blocks, "shared": g_shared}
        params, m, v = adam_update(params, grads, m, v, step=step, lr=lr,
                                   weight_decay=0.01)
        return params["blocks"], params["shared"], m, v, loss

    bspec = pspecs["blocks"]
    sspec = pspecs["shared"]
    state_spec = {"blocks": bspec, "shared": sspec}

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(bspec, sspec, state_spec, state_spec,
                  P("dp", "sp"), P("dp", "sp"), P("dp", "sp"), P()),
        out_specs=(bspec, sspec, state_spec, state_spec, P()),
        check_vma=False)

    @jax.jit
    def train_step(params, opt_state, tokens, targets, mask, step):
        m, v = opt_state
        blocks, shared, m, v, loss = sharded(
            params["blocks"], params["shared"], m, v, tokens, targets,
            mask, step)
        return {"blocks": blocks, "shared": shared}, (m, v), loss

    return train_step
