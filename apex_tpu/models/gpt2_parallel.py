"""Manually-parallel GPT-2 — the multi-chip training step: DP × TP × SP over a
``jax.sharding.Mesh`` via ``shard_map``.

Composition (the 'How to Scale Your Model' recipe, hand-annotated):
- **DP**: batch sharded over ``dp``; grads of every param psum over dp
  (the bucketed-psum DDP capability, apex_tpu.parallel.ddp).
- **TP**: Megatron column/row parallel linears over ``tp`` — q/k/v projections
  column-sharded (heads split), attention output row-sharded with a psum;
  MLP fc column-sharded, proj row-sharded with a psum. The wgrad-accum
  primitive semantics (fp32 grads for low-precision params) ride on
  preferred_element_type.
- **SP**: sequence sharded over ``sp``; attention runs the ring
  (apex_tpu.parallel.ring_attention) so K/V shards rotate over ICI while Q
  stays resident; positional embeddings sharded with the sequence.

Pipeline (pp) and expert (ep) axes: not yet wired (round-1 scope; the mesh
helper accepts them as size-1 axes so the step signature is stable).

All params/optimizer state live in fp32; compute in bf16 (amp O1 shape);
optimizer is the fused Adam tree update (optimizers/functional.py).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.models.gpt2 import GPT2Config
from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.normalization.fused_layer_norm import (fused_layer_norm_affine)
from apex_tpu.optimizers.functional import adam_update
from apex_tpu.parallel.ring_attention import ring_self_attention

_f32 = jnp.float32


def choose_mesh_shape(n: int) -> Tuple[int, int, int]:
    """Factor n devices into (dp, tp, sp), preferring dp ≥ tp ≥ sp."""
    dp = tp = sp = 1
    for axis in ("dp", "tp", "sp", "dp", "tp", "sp"):
        if n % 2 != 0 or n == 1:
            break
        n //= 2
        if axis == "dp":
            dp *= 2
        elif axis == "tp":
            tp *= 2
        else:
            sp *= 2
    dp *= n  # leftover odd factor onto dp
    return dp, tp, sp


def init_params(cfg: GPT2Config, key) -> Dict[str, Any]:
    """Full (unsharded) param dict; shard_map slices per the specs below."""
    ks = jax.random.split(key, 4 + cfg.n_layer)
    e = cfg.n_embd
    p = {
        "wte": jax.random.normal(ks[0], (cfg.vocab_size, e), _f32) * 0.02,
        "wpe": jax.random.normal(ks[1], (cfg.n_positions, e), _f32) * 0.01,
        "lnf_w": jnp.ones((e,), _f32),
        "lnf_b": jnp.zeros((e,), _f32),
        "blocks": [],
    }
    for i in range(cfg.n_layer):
        bk = jax.random.split(ks[4 + i], 6)
        std = 0.02
        p["blocks"].append({
            "ln1_w": jnp.ones((e,), _f32), "ln1_b": jnp.zeros((e,), _f32),
            "wq": jax.random.normal(bk[0], (e, e), _f32) * std,
            "wk": jax.random.normal(bk[1], (e, e), _f32) * std,
            "wv": jax.random.normal(bk[2], (e, e), _f32) * std,
            "wo": jax.random.normal(bk[3], (e, e), _f32) * std
                  / math.sqrt(2 * cfg.n_layer),
            "ln2_w": jnp.ones((e,), _f32), "ln2_b": jnp.zeros((e,), _f32),
            "fc_w": jax.random.normal(bk[4], (e, 4 * e), _f32) * std,
            "fc_b": jnp.zeros((4 * e,), _f32),
            "proj_w": jax.random.normal(bk[5], (4 * e, e), _f32) * std
                      / math.sqrt(2 * cfg.n_layer),
            "proj_b": jnp.zeros((e,), _f32),
        })
    return p


def param_specs(cfg: GPT2Config) -> Dict[str, Any]:
    """PartitionSpecs: TP-sharded projections, SP-sharded positions."""
    col = P(None, "tp")   # column parallel (output dim sharded)
    row = P("tp", None)   # row parallel (input dim sharded)
    rep = P()
    block = {
        "ln1_w": rep, "ln1_b": rep,
        "wq": col, "wk": col, "wv": col, "wo": row,
        "ln2_w": rep, "ln2_b": rep,
        "fc_w": col, "fc_b": P("tp"), "proj_w": row, "proj_b": rep,
    }
    return {
        "wte": rep,
        "wpe": P("sp", None),
        "lnf_w": rep, "lnf_b": rep,
        "blocks": [dict(block) for _ in range(cfg.n_layer)],
    }


def _grad_sync_specs(cfg: GPT2Config) -> Dict[str, Any]:
    """Axes each param's grad must be psum'd over = axes it is replicated on.
    Encoded as '|'-joined strings so the spec tree has leaf-for-leaf structure
    with the grad tree."""
    tp_sharded = "dp|sp"          # grads of tp-sharded params
    replicated = "dp|sp|tp"
    block = {
        "ln1_w": replicated, "ln1_b": replicated,
        "wq": tp_sharded, "wk": tp_sharded, "wv": tp_sharded,
        "wo": tp_sharded,
        "ln2_w": replicated, "ln2_b": replicated,
        "fc_w": tp_sharded, "fc_b": tp_sharded, "proj_w": tp_sharded,
        "proj_b": replicated,
    }
    return {
        "wte": replicated,
        "wpe": "dp|tp",           # sp-sharded: sum over dp and tp only
        "lnf_w": replicated, "lnf_b": replicated,
        "blocks": [dict(block) for _ in range(cfg.n_layer)],
    }


def _forward_local(cfg: GPT2Config, params, tokens, targets, mask):
    """Per-shard forward: tokens (b_local, s_local) on a (dp, tp, sp) mesh."""
    cd = cfg.compute_dtype
    e = cfg.n_embd
    tp = jax.lax.axis_size("tp")
    h_local = cfg.n_head // tp
    d = e // cfg.n_head

    # wpe is sp-sharded over positions; the parallel path trains at full
    # context length (seq == n_positions) so position shards align with
    # sequence shards
    sp = jax.lax.axis_size("sp")
    assert tokens.shape[1] * sp == cfg.n_positions, (
        f"parallel GPT-2 requires seq == n_positions "
        f"({tokens.shape[1]}*{sp} != {cfg.n_positions})")
    x = params["wte"][tokens].astype(cd) + params["wpe"][None].astype(cd)
    b, s_local, _ = x.shape

    for blk in params["blocks"]:
        y = fused_layer_norm_affine(x, blk["ln1_w"], blk["ln1_b"], e)
        q = (y @ blk["wq"].astype(cd))
        k = (y @ blk["wk"].astype(cd))
        v = (y @ blk["wv"].astype(cd))

        def heads(t):
            return t.reshape(b, s_local, h_local, d).transpose(0, 2, 1, 3)

        o = ring_self_attention(heads(q), heads(k), heads(v), "sp",
                                causal=True)
        o = o.transpose(0, 2, 1, 3).reshape(b, s_local, h_local * d)
        # row-parallel output projection: partial matmul + psum over tp
        attn = jax.lax.psum(o @ blk["wo"].astype(cd), "tp")
        x = x + attn

        y = fused_layer_norm_affine(x, blk["ln2_w"], blk["ln2_b"], e)
        hmid = jax.nn.gelu(y @ blk["fc_w"].astype(cd)
                           + blk["fc_b"].astype(cd), approximate=False)
        mlp = jax.lax.psum(hmid @ blk["proj_w"].astype(cd), "tp")
        x = x + (mlp + blk["proj_b"].astype(cd))

    x = fused_layer_norm_affine(x, params["lnf_w"], params["lnf_b"], e)
    logits = jax.lax.dot_general(x, params["wte"].astype(cd),
                                 (((2,), (1,)), ((), ())),
                                 preferred_element_type=_f32)
    loss_tok = softmax_cross_entropy_loss(logits, targets)
    # global masked mean over the dp × sp data shards
    tot = jax.lax.psum(jax.lax.psum(jnp.sum(loss_tok * mask), "dp"), "sp")
    cnt = jax.lax.psum(jax.lax.psum(jnp.sum(mask), "dp"), "sp")
    return tot / jnp.maximum(cnt, 1.0)


def make_train_step(cfg: GPT2Config, mesh: Mesh, lr: float = 1e-4):
    """Returns jitted train_step(params, opt_state, tokens, targets, mask, step)
    → (params, opt_state, loss). Inputs are FULL arrays; sharding via specs."""
    pspecs = param_specs(cfg)
    sync_axes = _grad_sync_specs(cfg)

    def local_step(params, m, v, tokens, targets, mask, step):
        def loss_fn(p):
            return _forward_local(cfg, p, tokens, targets, mask)

        loss, grads = jax.value_and_grad(loss_fn)(params)

        # gradient sync: psum over every axis the param is replicated on.
        # With check_vma=False shard_map does not track replication, so the
        # replicated loss seeds a cotangent on EVERY device and each psum
        # transpose re-broadcasts it — after the sync psums the result is
        # exactly (dp·tp·sp)× the true gradient, for every param class
        # (verified empirically across (2,1,1)...(8,1,1),(1,8,1),(4,2,1),
        # (1,2,4) meshes). Normalize by the total mesh size.
        n_total = (jax.lax.axis_size("dp") * jax.lax.axis_size("tp")
                   * jax.lax.axis_size("sp"))

        def sync(g, axes):
            for ax in axes.split("|"):
                g = jax.lax.psum(g, ax)
            return g / n_total

        grads = jax.tree_util.tree_map(sync, grads, sync_axes)

        params, m, v = adam_update(params, grads, m, v, step=step, lr=lr,
                                   weight_decay=0.01)
        return params, m, v, loss

    state_specs = pspecs  # optimizer state sharded exactly like its params

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(pspecs, state_specs, state_specs,
                  P("dp", "sp"), P("dp", "sp"), P("dp", "sp"), P()),
        out_specs=(pspecs, state_specs, state_specs, P()),
        check_vma=False)

    @jax.jit
    def train_step(params, opt_state, tokens, targets, mask, step):
        m, v = opt_state
        params, m, v, loss = sharded(params, m, v, tokens, targets, mask,
                                     step)
        return params, (m, v), loss

    return train_step


def init_opt_state(params):
    z = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, _f32), params)
    z2 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, _f32), params)
    return (z, z2)
