"""GPT-2 family — the flagship benchmark model (BASELINE.md config 5:
GPT-2 1.5B + megatron scaled_masked_softmax + fused MHA).

Built entirely from the framework's fused components: FusedLayerNorm (Pallas),
flash attention (Pallas, = fused MHA + causal megatron softmax),
dense_gelu_dense (fused MLP), fused xentropy loss. bf16-first compute with
fp32 params by default (amp O1 shape).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.normalization.fused_layer_norm import FusedLayerNorm
from apex_tpu.ops.pallas.flash_attention import flash_attention
from apex_tpu.transformer.fused_dense import dense_gelu_dense


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    compute_dtype: Any = jnp.bfloat16

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, n_positions=256, n_embd=256, n_layer=2,
                   n_head=4)

    @classmethod
    def small(cls):
        return cls()

    @classmethod
    def xl(cls):  # GPT-2 1.5B
        return cls(n_embd=1600, n_layer=48, n_head=25)


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        h = c.n_head
        d = c.n_embd // h
        b, s, e = x.shape

        y = FusedLayerNorm(e, name="ln_1")(x)
        qkv = nn.Dense(3 * e, dtype=c.compute_dtype, name="attn_qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        # ragged lengths are padded inside the kernel — no unfused fallback
        o = flash_attention(q, k, v, True)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
        x = x + nn.Dense(e, dtype=c.compute_dtype, name="attn_out")(o)

        y = FusedLayerNorm(e, name="ln_2")(x)
        w1 = self.param("mlp_fc_w", nn.initializers.normal(0.02),
                        (4 * e, e), jnp.float32)
        b1 = self.param("mlp_fc_b", nn.initializers.zeros, (4 * e,),
                        jnp.float32)
        w2 = self.param("mlp_proj_w", nn.initializers.normal(0.02),
                        (e, 4 * e), jnp.float32)
        b2 = self.param("mlp_proj_b", nn.initializers.zeros, (e,),
                        jnp.float32)
        x = x + dense_gelu_dense(y, w1.astype(c.compute_dtype),
                                 b1.astype(c.compute_dtype),
                                 w2.astype(c.compute_dtype),
                                 b2.astype(c.compute_dtype))
        return x


class GPT2(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False,
                 position_offset=0):
        """``position_offset`` shifts the learned positional embeddings:
        token column ``j`` reads ``wpe[position_offset + j]`` — the same
        offset contract as ``transformer.rope.fused_rope`` so a suffix of
        a sequence (a serving decode window) sees the rotations/embeddings
        of its absolute positions. Accepts a python int or a traced int32
        scalar; caller guarantees ``position_offset + s <= n_positions``.
        """
        c = self.cfg
        b, s = tokens.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (c.vocab_size, c.n_embd), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (c.n_positions, c.n_embd), jnp.float32)
        from apex_tpu.transformer.rope import _offset_slice

        pos = _offset_slice(wpe, position_offset, s)
        x = wte[tokens].astype(c.compute_dtype) \
            + pos[None].astype(c.compute_dtype)
        for i in range(c.n_layer):
            x = Block(c, name=f"h_{i}")(x)
        x = FusedLayerNorm(c.n_embd, name="ln_f")(x)
        if return_hidden:
            # pre-logits hidden states, for the chunked-vocab fused head
            # (transformer.linear_cross_entropy) — the logits matmul is
            # then fused into the loss and never materialized
            return x
        logits = jax.lax.dot_general(
            x, wte.astype(c.compute_dtype), (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return logits


def lm_loss(model: GPT2, params, tokens):
    """Next-token xentropy over the fused loss (contrib.xentropy)."""
    logits = model.apply(params, tokens)
    loss = softmax_cross_entropy_loss(logits[:, :-1], tokens[:, 1:])
    return jnp.mean(loss)


# --------------------------------------------------------------- serving
#
# The cache-aware forward used by apex_tpu.serve: ONE token per slot per
# call, attention over the slot's cached K/V, learned positional
# embeddings indexed by each slot's absolute position. It is a pure
# function over the SAME param pytree GPT2.init/flax produce (no separate
# serving weights), with every array shape fixed at [num_slots, ...] — the
# serve engine's single-compile invariant rests on that. The flash kernel
# is a training/prefill-batch device; at one query row per slot the MXU
# work is a [1, L] matvec, so decode attention is the chunked-softmax XLA
# path in serve.attention instead.


def _affine_layer_norm(x, scale, bias, eps: float = 1e-5):
    """Row LayerNorm for the decode path: the repo's jnp reference LN
    (the same normalization FusedLayerNorm computes — at num_slots rows
    there is no tile to amortize a Pallas launch over)."""
    from apex_tpu.normalization.fused_layer_norm import manual_layer_norm

    return manual_layer_norm(x, scale, bias, (x.shape[-1],), eps)


def gpt2_token_forward(cfg: GPT2Config, params, cache, tokens, positions,
                       write_mask, *, block_k=None, kv_quant=None,
                       final_scope: str = "sampling"):
    """One decode token per slot through GPT-2 with the serving KV cache.

    ``tokens``/``positions``/``write_mask``: ``[num_slots]`` (int32, int32,
    bool). Each masked slot's token K/V is appended to the cache at
    ``positions[slot]`` and the slot attends over cached positions
    ``0..positions[slot]``; masked-off slots compute garbage that is
    discarded and write nothing. Returns ``(logits [num_slots, vocab]
    fp32, cache)``. ``block_k`` is the decode-attention KV chunk
    (autotuned via ``apex_tpu.tune`` when None). ``final_scope`` names
    the phase of the final LN + logits projection for the cost ledger:
    decode/prefill feed the sampler ("sampling"); the speculative
    verify step passes "verify" so its per-position logits work — the
    verify step's own cost — is attributed to the verify phase and
    phase reconciliation stays exact (monitor/costs.py).

    ``cache`` may be either layout: the slot-contiguous
    :class:`~apex_tpu.serve.kv_cache.KVCache` or the paged
    :class:`~apex_tpu.serve.kv_cache.PagedKVCache`. The dispatch is
    static (an ``isinstance`` on the pytree class at trace time); the
    attention chunk arithmetic is shared, so the two layouts are
    bit-identical in fp32 on identical resident bytes at equal
    ``block_k`` (the chunk size orders the softmax partial sums).

    ``kv_quant`` (``"int8"``/``"mxfp8"``, static trace-time string) arms
    the block-scale KV codec: each appended token's K/V is encoded with
    one fp32 scale per head inside the write, and attention dequantizes
    per streamed chunk from the cache's scale planes. Encode is
    deterministic, so prefill and decode still produce bit-identical
    cache bytes for the same token at the same position (the PR-5
    invariant survives quantization).
    """
    from apex_tpu.serve.attention import cached_attention, paged_attention
    from apex_tpu.serve.kv_cache import paged_write_token, write_token

    # layout dispatch is structural, NOT isinstance: these imports are
    # function-local (the serve package imports this module), so a
    # purge-and-reimport of apex_tpu.serve.kv_cache mid-process would
    # make isinstance(cache, PagedKVCache) compare against a fresh class
    # and silently route a paged cache down the slot path
    paged = hasattr(cache, "page_table")

    c = cfg
    dt = c.compute_dtype
    h, d = c.n_head, c.n_embd // c.n_head
    p = params["params"] if "params" in params else params
    pos = positions.astype(jnp.int32)

    x = (p["wte"][tokens].astype(dt)
         + p["wpe"][jnp.clip(pos, 0, c.n_positions - 1)].astype(dt))
    # phase markers: trace-safe jax.named_scope only (scope names ride
    # the MLIR loc(...) metadata — monitor/costs.py attributes the cost
    # ledger per phase on them; no traced effect, APX001-quiet)
    for i in range(c.n_layer):
        blk = p[f"h_{i}"]
        with jax.named_scope("ln_qkv"):
            y = _affine_layer_norm(x, blk["ln_1"]["weight"],
                                   blk["ln_1"]["bias"])
            qkv = (y.astype(dt) @ blk["attn_qkv"]["kernel"].astype(dt)
                   + blk["attn_qkv"]["bias"].astype(dt))
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(-1, h, d)
            k = k.reshape(-1, h, d)
            v = v.reshape(-1, h, d)
        with jax.named_scope("attention"):
            if paged:
                cache = paged_write_token(cache, i, k, v, pos,
                                          write_mask, codec=kv_quant)
                o = paged_attention(
                    q, cache.k[i], cache.v[i], cache.page_table, pos,
                    block_k=block_k,
                    k_scale=(None if kv_quant is None
                             else cache.k_scale[i]),
                    v_scale=(None if kv_quant is None
                             else cache.v_scale[i]))
            else:
                cache = write_token(cache, i, k, v, pos, write_mask,
                                    codec=kv_quant)
                o = cached_attention(
                    q, cache.k[i], cache.v[i], pos, block_k=block_k,
                    k_scale=(None if kv_quant is None
                             else cache.k_scale[i]),
                    v_scale=(None if kv_quant is None
                             else cache.v_scale[i]))
            o = o.reshape(-1, c.n_embd)
            x = x + (o.astype(dt) @ blk["attn_out"]["kernel"].astype(dt)
                     + blk["attn_out"]["bias"].astype(dt))
        with jax.named_scope("mlp"):
            y = _affine_layer_norm(x, blk["ln_2"]["weight"],
                                   blk["ln_2"]["bias"])
            x = x + dense_gelu_dense(y, blk["mlp_fc_w"].astype(dt),
                                     blk["mlp_fc_b"].astype(dt),
                                     blk["mlp_proj_w"].astype(dt),
                                     blk["mlp_proj_b"].astype(dt))
    with jax.named_scope(final_scope):
        x = _affine_layer_norm(x, p["ln_f"]["weight"], p["ln_f"]["bias"])
        logits = jax.lax.dot_general(
            x, p["wte"].astype(dt), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    return logits, cache


def _psum_halves_into(part, resid, bias, axis_name, ln=None):
    """TokenWeave overlap seam: one logical all-reduce of ``part``
    ``[num_slots, e]`` split into two slot-half psums, each half's
    residual add (+ optional row layer-norm) interleaved so the OTHER
    half's collective can fly behind it under XLA's async-collective
    scheduling. Row-wise ops make the halved compute bit-identical to
    the full-width spelling, and an elementwise psum split along rows is
    bit-identical to the unsplit psum — so "overlap" differs from plain
    Megatron row-parallel only in schedule, never in value. Returns
    ``(x, ln_x | None)``."""
    half = part.shape[0] // 2
    with jax.named_scope("collective"):
        r1 = jax.lax.psum(part[:half], axis_name)
    x1 = resid[:half] + r1 + bias
    y1 = ln(x1) if ln is not None else None
    with jax.named_scope("collective"):
        r2 = jax.lax.psum(part[half:], axis_name)
    x2 = resid[half:] + r2 + bias
    y2 = ln(x2) if ln is not None else None
    x = jnp.concatenate([x1, x2], axis=0)
    return x, (jnp.concatenate([y1, y2], axis=0) if ln is not None
               else None)


def gpt2_token_forward_tp(cfg: GPT2Config, tp: int, sync: str, params,
                          cache, tokens, positions, write_mask, *,
                          block_k=None, kv_quant=None,
                          axis_name: str = "tp",
                          final_scope: str = "sampling"):
    """The PER-RANK body of the tensor-parallel single-token forward —
    run under ``shard_map`` over the serving mesh (``apex_tpu.serve.tp``
    owns the param layout and specs). Heads are sharded: this rank sees
    ``n_head // tp`` heads' qkv columns, its slice of the KV cache's
    head axis, and the replicated residual stream.

    The rank-local arithmetic is :func:`gpt2_token_forward`'s, op for
    op, on column slices (per-column matmul determinism is what the
    bit-exactness claim rides on); the modes differ ONLY in how ranks
    combine:

    - ``sync="exact"``: ``all_gather`` (concatenation — no cross-rank
      float add) of the attention heads and the MLP hidden slices, then
      the full projection matmuls replicated. Bit-identical in fp32 to
      the single-chip forward at equal ``block_k``.
    - ``sync="overlap"``: Megatron row-parallel projections; each of the
      two per-layer all-reduces is split into two slot-half psums
      interleaved with the adjacent residual/norm compute (TokenWeave).
      ±ulp vs exact (partial sums reorder float adds).
    - ``sync="relaxed"``: the post-attention all-reduce is deferred —
      ``ln_2``/MLP run on the rank's partially-synchronized residual and
      ONE combined psum per layer lands attention + MLP together
      (partially-synchronized activations; opt-in approximation).

    Every mode re-synchronizes the residual stream by the end of each
    layer, so ``ln_f`` and the logits matmul run replicated and the
    returned logits are identical on every rank (the caller's
    ``out_specs`` treat them as replicated).
    """
    from apex_tpu.serve.attention import cached_attention, paged_attention
    from apex_tpu.serve.kv_cache import paged_write_token, write_token

    paged = hasattr(cache, "page_table")
    c = cfg
    dt = c.compute_dtype
    h_loc = c.n_head // tp
    d = c.n_embd // c.n_head
    p = params
    pos = positions.astype(jnp.int32)

    x = (p["wte"][tokens].astype(dt)
         + p["wpe"][jnp.clip(pos, 0, c.n_positions - 1)].astype(dt))
    # phase markers mirror gpt2_token_forward's; collective sites carry
    # their own nested "collective" scope (innermost scope wins in the
    # ledger walk, so a gather inside attention attributes to collective)
    for i in range(c.n_layer):
        blk = p[f"h_{i}"]
        with jax.named_scope("ln_qkv"):
            y = _affine_layer_norm(x, blk["ln_1"]["weight"],
                                   blk["ln_1"]["bias"])
            # local heads' q/k/v: the permuted kernel slice is exactly
            # this rank's columns of the full projection, so each output
            # column's dot product is the single-chip one
            qkv = (y.astype(dt) @ blk["attn_qkv"]["kernel"].astype(dt)
                   + blk["attn_qkv"]["bias"].astype(dt))
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(-1, h_loc, d)
            k = k.reshape(-1, h_loc, d)
            v = v.reshape(-1, h_loc, d)
        with jax.named_scope("attention"):
            # per-head encode is rank-local (a head's scale reduces only
            # over that head's head_dim), so this rank's shard of the
            # quantized pool is bit-identical to the single-chip
            # engine's same head slice
            if paged:
                cache = paged_write_token(cache, i, k, v, pos,
                                          write_mask, codec=kv_quant)
                o = paged_attention(
                    q, cache.k[i], cache.v[i], cache.page_table, pos,
                    block_k=block_k,
                    k_scale=(None if kv_quant is None
                             else cache.k_scale[i]),
                    v_scale=(None if kv_quant is None
                             else cache.v_scale[i]))
            else:
                cache = write_token(cache, i, k, v, pos, write_mask,
                                    codec=kv_quant)
                o = cached_attention(
                    q, cache.k[i], cache.v[i], pos, block_k=block_k,
                    k_scale=(None if kv_quant is None
                             else cache.k_scale[i]),
                    v_scale=(None if kv_quant is None
                             else cache.v_scale[i]))
            out_b = blk["attn_out"]["bias"].astype(dt)
            if sync == "exact":
                # concatenate the heads across ranks, then the FULL
                # output projection replicated: no float add crosses a
                # rank
                with jax.named_scope("collective"):
                    o_full = jax.lax.all_gather(o, axis_name, axis=1,
                                                tiled=True)
                o_full = o_full.reshape(-1, c.n_embd)
                x = x + (o_full.astype(dt)
                         @ blk["attn_out"]["kernel"].astype(dt) + out_b)
            else:
                # row-parallel output projection: this rank's heads hit
                # its rows of the kernel — a PARTIAL [num_slots, e] sum
                attn_part = (o.reshape(-1, h_loc * d).astype(dt)
                             @ blk["attn_out"]["kernel"].astype(dt))
        with jax.named_scope("mlp"):
            if sync == "exact":
                y = _affine_layer_norm(x, blk["ln_2"]["weight"],
                                       blk["ln_2"]["bias"])
            elif sync == "overlap":
                x, y = _psum_halves_into(
                    attn_part, x, out_b, axis_name,
                    ln=lambda v_: _affine_layer_norm(
                        v_, blk["ln_2"]["weight"], blk["ln_2"]["bias"]))
            else:  # relaxed: defer the attention psum across the norm
                y = _affine_layer_norm(x + attn_part + out_b,
                                       blk["ln_2"]["weight"],
                                       blk["ln_2"]["bias"])
            # MLP, column-parallel fc (this rank's 4e/tp rows),
            # mirroring fused_dense.dense_gelu_dense's primal ops exactly
            h = jax.lax.dot_general(
                y.astype(dt), blk["mlp_fc_w"].astype(dt),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            h = h + blk["mlp_fc_b"].astype(jnp.float32)
            a = jax.nn.gelu(h, approximate=False)
            proj_b = blk["mlp_proj_b"].astype(jnp.float32).astype(dt)
            if sync == "exact":
                with jax.named_scope("collective"):
                    a_full = jax.lax.all_gather(a.astype(dt), axis_name,
                                                axis=1, tiled=True)
                m = jax.lax.dot_general(
                    a_full, blk["mlp_proj_w"].astype(dt),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                x = x + (m
                         + blk["mlp_proj_b"].astype(jnp.float32)
                         ).astype(dt)
            else:
                mlp_part = jax.lax.dot_general(
                    a.astype(dt), blk["mlp_proj_w"].astype(dt),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32).astype(dt)
                if sync == "overlap":
                    x, _ = _psum_halves_into(mlp_part, x, proj_b,
                                             axis_name)
                else:
                    # relaxed: ONE all-reduce lands the deferred
                    # attention partial and the MLP partial together;
                    # the residual stream is fully synchronized again at
                    # layer exit
                    x, _ = _psum_halves_into(attn_part + mlp_part, x,
                                             out_b + proj_b, axis_name)
    with jax.named_scope(final_scope):
        x = _affine_layer_norm(x, p["ln_f"]["weight"], p["ln_f"]["bias"])
        logits = jax.lax.dot_general(
            x, p["wte"].astype(dt), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    return logits, cache
