"""GPT-2 family — the flagship benchmark model (BASELINE.md config 5:
GPT-2 1.5B + megatron scaled_masked_softmax + fused MHA).

Built entirely from the framework's fused components: FusedLayerNorm (Pallas),
flash attention (Pallas, = fused MHA + causal megatron softmax),
dense_gelu_dense (fused MLP), fused xentropy loss. bf16-first compute with
fp32 params by default (amp O1 shape).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
from apex_tpu.normalization.fused_layer_norm import FusedLayerNorm
from apex_tpu.ops.pallas.flash_attention import flash_attention
from apex_tpu.transformer.fused_dense import dense_gelu_dense


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    compute_dtype: Any = jnp.bfloat16

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, n_positions=256, n_embd=256, n_layer=2,
                   n_head=4)

    @classmethod
    def small(cls):
        return cls()

    @classmethod
    def xl(cls):  # GPT-2 1.5B
        return cls(n_embd=1600, n_layer=48, n_head=25)


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        h = c.n_head
        d = c.n_embd // h
        b, s, e = x.shape

        y = FusedLayerNorm(e, name="ln_1")(x)
        qkv = nn.Dense(3 * e, dtype=c.compute_dtype, name="attn_qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        # ragged lengths are padded inside the kernel — no unfused fallback
        o = flash_attention(q, k, v, True)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, e)
        x = x + nn.Dense(e, dtype=c.compute_dtype, name="attn_out")(o)

        y = FusedLayerNorm(e, name="ln_2")(x)
        w1 = self.param("mlp_fc_w", nn.initializers.normal(0.02),
                        (4 * e, e), jnp.float32)
        b1 = self.param("mlp_fc_b", nn.initializers.zeros, (4 * e,),
                        jnp.float32)
        w2 = self.param("mlp_proj_w", nn.initializers.normal(0.02),
                        (e, 4 * e), jnp.float32)
        b2 = self.param("mlp_proj_b", nn.initializers.zeros, (e,),
                        jnp.float32)
        x = x + dense_gelu_dense(y, w1.astype(c.compute_dtype),
                                 b1.astype(c.compute_dtype),
                                 w2.astype(c.compute_dtype),
                                 b2.astype(c.compute_dtype))
        return x


class GPT2(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False):
        c = self.cfg
        b, s = tokens.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (c.vocab_size, c.n_embd), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (c.n_positions, c.n_embd), jnp.float32)
        x = wte[tokens].astype(c.compute_dtype) \
            + wpe[:s][None].astype(c.compute_dtype)
        for i in range(c.n_layer):
            x = Block(c, name=f"h_{i}")(x)
        x = FusedLayerNorm(c.n_embd, name="ln_f")(x)
        if return_hidden:
            # pre-logits hidden states, for the chunked-vocab fused head
            # (transformer.linear_cross_entropy) — the logits matmul is
            # then fused into the loss and never materialized
            return x
        logits = jax.lax.dot_general(
            x, wte.astype(c.compute_dtype), (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return logits


def lm_loss(model: GPT2, params, tokens):
    """Next-token xentropy over the fused loss (contrib.xentropy)."""
    logits = model.apply(params, tokens)
    loss = softmax_cross_entropy_loss(logits[:, :-1], tokens[:, 1:])
    return jnp.mean(loss)
