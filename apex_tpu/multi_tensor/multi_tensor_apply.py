"""``multi_tensor_applier`` facade — parity with
``apex/multi_tensor_apply/multi_tensor_apply.py:1-27``.

The reference wraps every amp_C kernel behind
``multi_tensor_applier(op, noop_flag_buffer, tensor_lists, *args)``. On TPU the
"op" is a jittable functor over same-length lists of arrays; one traced call
covers the whole list (the XLA analog of one chunked kernel launch over ≤110
tensors, csrc/multi_tensor_apply.cuh:13-23).

``noop_flag`` becomes a returned ``found_inf`` scalar instead of a mutated
buffer — callers predicate their update with ``jnp.where`` (functional JAX has
no in-place side channel).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence


class MultiTensorApply:
    """Callable singleton mirroring ``MultiTensorApply(2048*32)``.

    ``chunk_size`` is accepted for API parity; XLA chooses its own tiling so it
    is advisory only.
    """

    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op: Callable, noop_flag: Any,
                 tensor_lists: Sequence[Sequence], *args):
        """Apply ``op(tensor_lists, *args)``; returns whatever op returns.

        ``noop_flag`` is ignored (kept for signature parity with
        multi_tensor_apply.py:24-27); ops return found_inf explicitly.
        """
        return op(tensor_lists, *args)


multi_tensor_applier = MultiTensorApply(2048 * 32)
