"""Fused multi-tensor primitives — TPU equivalent of the ``amp_C`` kernel family.

Reference kernels (all built on ``csrc/multi_tensor_apply.cuh:32-103``):
- ``multi_tensor_scale``      csrc/multi_tensor_scale_kernel.cu   (out = in*scale + inf check)
- ``multi_tensor_axpby``      csrc/multi_tensor_axpby_kernel.cu   (out = a*x + b*y + inf check)
- ``multi_tensor_l2norm``     csrc/multi_tensor_l2norm_kernel.cu  (global + per-tensor norms)
- ``multi_tensor_unscale_l2norm``  csrc/amp_C_frontend.cpp:13-28  (fused unscale + norm)
- ``update_scale_hysteresis`` csrc/update_scale_hysteresis.cu:5-41

TPU design: the reference's win is one kernel launch over ~110 tensors instead of
hundreds of launches. Under ``jax.jit`` the whole pytree update traces into ONE XLA
program and the elementwise work fuses into a handful of HBM-bandwidth-bound fused
loops — the launch-overhead problem the CUDA harness solves does not exist. What we
keep from the reference is the *semantics*: a single ``found_inf`` no-op flag
predicating the whole update (``noop_flag`` in the CUDA kernels), fp32 math
irrespective of storage dtype, and global-norm reductions computed alongside.

Everything here is a pure jittable function over pytrees.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def tree_check_finite(tree: Any) -> jax.Array:
    """Public found_inf check: True if ANY element of the pytree is inf/nan,
    without materializing any scaled copy (cheapest possible overflow probe)."""
    return _tree_any_nonfinite(tree)


def _tree_any_nonfinite(tree: Any) -> jax.Array:
    """found_inf over a pytree: True if any element is inf/nan.

    Mirrors the ``noop_flag`` side-channel every amp_C kernel writes
    (e.g. csrc/multi_tensor_scale_kernel.cu ``ScaleFunctor``).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.bool_)
    flags = [~jnp.all(jnp.isfinite(l.astype(jnp.float32))) for l in leaves]
    acc = flags[0]
    for f in flags[1:]:
        acc = acc | f
    return acc


def multi_tensor_scale(tree: Any, scale: jax.Array | float,
                       check_finite: bool = True) -> Tuple[Any, jax.Array]:
    """``out = in * scale`` with inf/nan detection (the loss-(un)scaling primitive).

    Returns ``(scaled_tree, found_inf)``. Math in fp32, output in input dtype —
    matching ``ScaleFunctor``'s load-as-fp32 behavior.
    """
    scale = jnp.asarray(scale, jnp.float32)

    def _s(x):
        return (x.astype(jnp.float32) * scale).astype(x.dtype)

    out = jax.tree_util.tree_map(_s, tree)
    found_inf = (_tree_any_nonfinite(tree) if check_finite
                 else jnp.zeros((), jnp.bool_))
    return out, found_inf


def multi_tensor_axpby(a: jax.Array | float, x_tree: Any,
                       b: jax.Array | float, y_tree: Any,
                       out_dtype=None) -> Tuple[Any, jax.Array]:
    """``out = a*x + b*y`` + inf check (master-grad accumulation primitive).

    Reference: csrc/multi_tensor_axpby_kernel.cu ``AxpbyFunctor``.
    """
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    def _axpby(x, y):
        r = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        return r.astype(out_dtype or x.dtype)

    out = jax.tree_util.tree_map(_axpby, x_tree, y_tree)
    return out, _tree_any_nonfinite(out)


def multi_tensor_l2norm(tree: Any, per_tensor: bool = False):
    """Global L2 norm across a pytree, optionally per-tensor norms too.

    Reference: csrc/multi_tensor_l2norm_kernel.cu (two-stage per-chunk partials +
    ``cleanup`` reduction). XLA's reduction already tiles this; we accumulate in
    fp32 like the kernel's ``float`` accumulators.

    Returns ``(global_norm, per_tensor_norms|None)`` with fp32 scalars.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        z = jnp.zeros((), jnp.float32)
        return z, (jnp.zeros((0,), jnp.float32) if per_tensor else None)
    sqs = [jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves]
    total = sqs[0]
    for s in sqs[1:]:
        total = total + s
    gnorm = jnp.sqrt(total)
    if per_tensor:
        return gnorm, jnp.sqrt(jnp.stack(sqs))
    return gnorm, None


def multi_tensor_unscale_l2norm(tree: Any, inv_scale: jax.Array | float,
                                per_tensor: bool = False):
    """Fused unscale + L2 norm (ref csrc/amp_C_frontend.cpp:13-28).

    Returns ``(unscaled_tree, global_norm, per_tensor_norms|None, found_inf)``.
    """
    inv_scale = jnp.asarray(inv_scale, jnp.float32)

    def _u(x):
        return (x.astype(jnp.float32) * inv_scale).astype(x.dtype)

    out = jax.tree_util.tree_map(_u, tree)
    gnorm, pt = multi_tensor_l2norm(out, per_tensor)
    return out, gnorm, pt, _tree_any_nonfinite(tree)


def update_scale_hysteresis(scale: jax.Array, growth_tracker: jax.Array,
                            hysteresis_tracker: jax.Array, found_inf: jax.Array,
                            growth_factor: float = 2.0,
                            backoff_factor: float = 0.5,
                            growth_interval: int = 2000,
                            hysteresis: int = 1):
    """Dynamic loss-scale growth/backoff with hysteresis.

    Jittable port of the single-thread state machine in
    csrc/update_scale_hysteresis.cu:5-41, matching it branch for branch:
      - found_inf: hysteresis -= 1; while still > 0 only the growth tracker
        resets (no backoff yet); once ≤ 0, every further inf step backs the
        scale off. Hysteresis is NOT replenished by a backoff.
      - clean step: growth_tracker += 1; at growth_interval the scale grows
        only if the result is finite (no growth past fp32 max); hysteresis is
        replenished to full.

    Returns ``(scale, growth_tracker, hysteresis_tracker)`` as jnp scalars.
    """
    scale = jnp.asarray(scale, jnp.float32)
    growth_tracker = jnp.asarray(growth_tracker, jnp.int32)
    hysteresis_tracker = jnp.asarray(hysteresis_tracker, jnp.int32)
    found_inf = jnp.asarray(found_inf, jnp.bool_)

    # found_inf branch
    hys_after = hysteresis_tracker - 1
    backoff_now = found_inf & (hys_after <= 0)
    scale_inf = jnp.where(backoff_now, scale * backoff_factor, scale)

    # clean branch
    gt_after = growth_tracker + 1
    grow_now = gt_after == growth_interval
    grown = scale * growth_factor
    grown = jnp.where(jnp.isfinite(grown), grown, scale)
    scale_ok = jnp.where(grow_now, grown, scale)
    gt_ok = jnp.where(grow_now, jnp.int32(0), gt_after)

    new_scale = jnp.where(found_inf, scale_inf, scale_ok)
    new_gt = jnp.where(found_inf, jnp.int32(0), gt_ok)
    new_hys = jnp.where(found_inf, hys_after, jnp.int32(hysteresis))
    return new_scale, new_gt, new_hys
