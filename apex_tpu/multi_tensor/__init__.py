from apex_tpu.multi_tensor.functional import (  # noqa: F401
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_unscale_l2norm,
    tree_check_finite,
    update_scale_hysteresis,
)
from apex_tpu.multi_tensor.multi_tensor_apply import (  # noqa: F401
    MultiTensorApply,
    multi_tensor_applier,
)
