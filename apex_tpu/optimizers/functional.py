"""Pure jittable optimizer updates over pytrees — the math of apex's fused optimizers.

Each function mirrors one CUDA functor from the reference:
- ``adam_update``      — ``AdamFunctor`` csrc/multi_tensor_adam.cu:24 (mode 0=L2, 1=AdamW)
- ``sgd_update``       — ``SGDFunctor`` csrc/multi_tensor_sgd_kernel.cu (momentum,
  dampening, nesterov, wd before/after momentum)
- ``lamb_update``      — ``LAMBStage1Functor``/``LAMBStage2Functor``
  csrc/multi_tensor_lamb.cu (update term + per-tensor trust ratio)
- ``novograd_update``  — ``NovoGradFunctor`` csrc/multi_tensor_novograd.cu
  (per-tensor 2nd-moment norm)
- ``adagrad_update``   — ``AdagradFunctor`` csrc/multi_tensor_adagrad.cu

Conventions shared with the reference kernels: all math in fp32 regardless of
storage dtype; a ``found_inf`` flag turns the whole update into a no-op
(the ``noop_flag`` of csrc/multi_tensor_apply.cuh); grads may carry a loss
scale, removed via ``inv_scale``. When a ``master`` tree (fp32) is given the
master is updated and params are its low-precision cast (amp O2 semantics).

Under one ``jax.jit`` these tree_maps trace into a single XLA program whose
elementwise chains fuse — the TPU analog of one multi_tensor_apply launch.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor.functional import multi_tensor_l2norm

_f32 = jnp.float32


def _keep(noop, old, new):
    return jax.tree_util.tree_map(
        lambda o, n: jnp.where(noop, o.astype(_f32), n).astype(o.dtype)
        if o.dtype != _f32 else jnp.where(noop, o, n), old, new)


def _prep(found_inf):
    return jnp.asarray(found_inf, jnp.bool_)


def adam_update(params: Any, grads: Any, exp_avg: Any, exp_avg_sq: Any, *,
                step, lr, beta1: float = 0.9, beta2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0,
                adam_w_mode: bool = True, bias_correction: bool = True,
                inv_scale=1.0, found_inf=False,
                master: Optional[Any] = None):
    """Fused Adam/AdamW tree update. Returns ``(params, m, v[, master])``."""
    noop = _prep(found_inf)
    stepf = jnp.asarray(step, _f32)
    lr = jnp.asarray(lr, _f32)
    inv_scale = jnp.asarray(inv_scale, _f32)
    if bias_correction:
        bc1 = 1.0 - jnp.power(_f32(beta1), stepf)
        bc2 = 1.0 - jnp.power(_f32(beta2), stepf)
    else:
        bc1 = bc2 = _f32(1.0)

    src = master if master is not None else params

    def _leaf(p, g, m, v):
        p32 = p.astype(_f32)
        g32 = g.astype(_f32) * inv_scale
        m32 = m.astype(_f32)
        v32 = v.astype(_f32)
        if not adam_w_mode:
            g32 = g32 + weight_decay * p32
        m_new = beta1 * m32 + (1.0 - beta1) * g32
        v_new = beta2 * v32 + (1.0 - beta2) * g32 * g32
        upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if adam_w_mode:
            upd = upd + weight_decay * p32
        return p32 - lr * upd, m_new, v_new

    new = jax.tree_util.tree_map(_leaf, src, grads, exp_avg, exp_avg_sq)
    p_new = jax.tree_util.tree_map(lambda t: t[0], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    m_new = jax.tree_util.tree_map(lambda t: t[1], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    v_new = jax.tree_util.tree_map(lambda t: t[2], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    m_out = _keep(noop, exp_avg, m_new)
    v_out = _keep(noop, exp_avg_sq, v_new)
    if master is not None:
        master_out = _keep(noop, master, p_new)
        p_out = jax.tree_util.tree_map(
            lambda p, pm: jnp.where(noop, p.astype(_f32),
                                    pm.astype(_f32)).astype(p.dtype),
            params, master_out)
        return p_out, m_out, v_out, master_out
    p_out = _keep(noop, params, p_new)
    return p_out, m_out, v_out


def sgd_update(params: Any, grads: Any, momentum_buf: Any, *,
               lr, momentum: float = 0.0, dampening: float = 0.0,
               weight_decay: float = 0.0, nesterov: bool = False,
               wd_after_momentum: bool = False, first_step=False,
               inv_scale=1.0, found_inf=False, master: Optional[Any] = None):
    """Fused SGD tree update (csrc/multi_tensor_sgd_kernel.cu ``SGDFunctor``).

    Returns ``(params, momentum_buf[, master])``. ``first_step`` may be a traced
    bool — on the first step the momentum buffer is initialized to the
    (wd-adjusted) gradient, matching torch/apex semantics.
    """
    noop = _prep(found_inf)
    lr = jnp.asarray(lr, _f32)
    inv_scale = jnp.asarray(inv_scale, _f32)
    first = jnp.asarray(first_step, jnp.bool_)
    src = master if master is not None else params

    def _leaf(p, g, b):
        p32 = p.astype(_f32)
        g32 = g.astype(_f32) * inv_scale
        b32 = b.astype(_f32)
        if weight_decay != 0.0 and not wd_after_momentum:
            g32 = g32 + weight_decay * p32
        if momentum != 0.0:
            b_new = jnp.where(first, g32,
                              momentum * b32 + (1.0 - dampening) * g32)
            d = g32 + momentum * b_new if nesterov else b_new
        else:
            b_new = b32
            d = g32
        if weight_decay != 0.0 and wd_after_momentum:
            d = d + weight_decay * p32
        return p32 - lr * d, b_new

    new = jax.tree_util.tree_map(_leaf, src, grads, momentum_buf)
    p_new = jax.tree_util.tree_map(lambda t: t[0], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    b_new = jax.tree_util.tree_map(lambda t: t[1], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    b_out = _keep(noop, momentum_buf, b_new)
    if master is not None:
        master_out = _keep(noop, master, p_new)
        p_out = jax.tree_util.tree_map(
            lambda p, pm: jnp.where(noop, p.astype(_f32),
                                    pm.astype(_f32)).astype(p.dtype),
            params, master_out)
        return p_out, b_out, master_out
    return _keep(noop, params, p_new), b_out


def lamb_update(params: Any, grads: Any, exp_avg: Any, exp_avg_sq: Any, *,
                step, lr, beta1: float = 0.9, beta2: float = 0.999,
                eps: float = 1e-6, weight_decay: float = 0.01,
                bias_correction: bool = True, grad_averaging: bool = True,
                max_grad_norm: float = 1.0, use_nvlamb: bool = False,
                adam_w_mode: bool = True, inv_scale=1.0, found_inf=False):
    """Fused LAMB tree update (two-phase like apex/optimizers/fused_lamb.py:145-242):
    global grad-norm clip, Adam-style update term, per-tensor trust ratio.

    Returns ``(params, m, v, global_grad_norm)``.
    """
    noop = _prep(found_inf)
    stepf = jnp.asarray(step, _f32)
    lr = jnp.asarray(lr, _f32)
    inv_scale = jnp.asarray(inv_scale, _f32)

    grads32 = jax.tree_util.tree_map(
        lambda g: g.astype(_f32) * inv_scale, grads)
    gnorm, _ = multi_tensor_l2norm(grads32)
    # clip global grad norm (fused_lamb.py:193-206: clip_global_grad_norm)
    if max_grad_norm is not None and max_grad_norm > 0:
        clip = jnp.maximum(gnorm / max_grad_norm, 1.0)
    else:
        clip = _f32(1.0)

    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - jnp.power(_f32(beta1), stepf)
        bc2 = 1.0 - jnp.power(_f32(beta2), stepf)
    else:
        bc1 = bc2 = _f32(1.0)

    def _leaf(p, g, m, v):
        p32 = p.astype(_f32)
        g32 = g / clip
        if not adam_w_mode:
            g32 = g32 + weight_decay * p32
        m_new = beta1 * m.astype(_f32) + beta3 * g32
        v_new = beta2 * v.astype(_f32) + (1.0 - beta2) * g32 * g32
        upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            upd = upd + weight_decay * p32
        # trust ratio (LAMBStage2Functor): ratio = w_norm/u_norm when both > 0
        w_norm = jnp.sqrt(jnp.sum(p32 * p32))
        u_norm = jnp.sqrt(jnp.sum(upd * upd))
        if use_nvlamb:
            ratio = jnp.where(u_norm > 0, w_norm / u_norm, 1.0)
        else:
            ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return p32 - lr * ratio * upd, m_new, v_new

    new = jax.tree_util.tree_map(_leaf, params, grads32, exp_avg, exp_avg_sq)
    p_new = jax.tree_util.tree_map(lambda t: t[0], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    m_new = jax.tree_util.tree_map(lambda t: t[1], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    v_new = jax.tree_util.tree_map(lambda t: t[2], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    return (_keep(noop, params, p_new), _keep(noop, exp_avg, m_new),
            _keep(noop, exp_avg_sq, v_new), gnorm)


def novograd_update(params: Any, grads: Any, exp_avg: Any, exp_avg_sq: Any, *,
                    step, lr, beta1: float = 0.95, beta2: float = 0.98,
                    eps: float = 1e-8, weight_decay: float = 0.0,
                    grad_averaging: bool = False, bias_correction: bool = False,
                    norm_type: int = 2, init_zero: bool = False,
                    inv_scale=1.0, found_inf=False):
    """Fused NovoGrad tree update (csrc/multi_tensor_novograd.cu).

    ``exp_avg_sq`` is a per-tensor scalar tree (the per-layer 2nd-moment norm,
    fused_novograd.py:126+). Returns ``(params, m, v)``.
    """
    noop = _prep(found_inf)
    stepf = jnp.asarray(step, _f32)
    lr = jnp.asarray(lr, _f32)
    inv_scale = jnp.asarray(inv_scale, _f32)
    first = stepf <= 1.0
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    if bias_correction:
        bc1 = 1.0 - jnp.power(_f32(beta1), stepf)
        bc2 = 1.0 - jnp.power(_f32(beta2), stepf)
    else:
        bc1 = bc2 = _f32(1.0)

    def _leaf(p, g, m, v):
        p32 = p.astype(_f32)
        g32 = g.astype(_f32) * inv_scale
        gnorm_sq = jnp.sum(g32 * g32)
        if norm_type == 0:
            gn = jnp.max(jnp.abs(g32))
        else:
            gn = jnp.sqrt(gnorm_sq)
        if init_zero:
            v_new = beta2 * v.astype(_f32) + (1.0 - beta2) * gn * gn \
                if norm_type == 2 else jnp.maximum(beta2 * v.astype(_f32), gn)
            v_new = jnp.where(first, (1.0 - beta2) * gn * gn, v_new) \
                if norm_type == 2 else v_new
        else:
            v_upd = beta2 * v.astype(_f32) + (1.0 - beta2) * gn * gn \
                if norm_type == 2 else jnp.maximum(beta2 * v.astype(_f32), gn)
            v_new = jnp.where(first, gn * gn if norm_type == 2 else gn, v_upd)
        denom = jnp.sqrt(v_new / bc2) + eps if norm_type == 2 \
            else v_new / bc2 + eps
        gg = g32 / denom
        if weight_decay != 0.0:
            gg = gg + weight_decay * p32
        m_new = beta1 * m.astype(_f32) + beta3 * gg
        upd = m_new / bc1
        return p32 - lr * upd, m_new, v_new

    new = jax.tree_util.tree_map(_leaf, params, grads, exp_avg, exp_avg_sq)
    p_new = jax.tree_util.tree_map(lambda t: t[0], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    m_new = jax.tree_util.tree_map(lambda t: t[1], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    v_new = jax.tree_util.tree_map(lambda t: t[2], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    return (_keep(noop, params, p_new), _keep(noop, exp_avg, m_new),
            _keep(noop, exp_avg_sq, v_new))


def adagrad_update(params: Any, grads: Any, state_sum: Any, *,
                   lr, eps: float = 1e-10, weight_decay: float = 0.0,
                   adagrad_w_mode: bool = False, inv_scale=1.0,
                   found_inf=False):
    """Fused Adagrad tree update (csrc/multi_tensor_adagrad.cu ``AdagradFunctor``).

    Returns ``(params, state_sum)``.
    """
    noop = _prep(found_inf)
    lr = jnp.asarray(lr, _f32)
    inv_scale = jnp.asarray(inv_scale, _f32)

    def _leaf(p, g, h):
        p32 = p.astype(_f32)
        g32 = g.astype(_f32) * inv_scale
        if not adagrad_w_mode and weight_decay != 0.0:
            g32 = g32 + weight_decay * p32
        h_new = h.astype(_f32) + g32 * g32
        upd = g32 / (jnp.sqrt(h_new) + eps)
        if adagrad_w_mode and weight_decay != 0.0:
            upd = upd + weight_decay * p32
        return p32 - lr * upd, h_new

    new = jax.tree_util.tree_map(_leaf, params, grads, state_sum)
    p_new = jax.tree_util.tree_map(lambda t: t[0], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    h_new = jax.tree_util.tree_map(lambda t: t[1], new,
                                   is_leaf=lambda t: isinstance(t, tuple))
    return _keep(noop, params, p_new), _keep(noop, state_sum, h_new)
