"""FusedMixedPrecisionLamb — TPU equivalent of
``apex/optimizers/fused_mixed_precision_lamb.py``.

LAMB with full-precision (fp32) optimizer state and master weights while the
model params are low precision (bf16/fp16); device-tensor ``step``/``lr`` and
GradScaler-awareness (:166) are inherent under jit. Uses the ``*_mp`` kernel
semantics (multi_tensor_l2norm_mp / multi_tensor_lamb_mp, :55-58): norms and
update math on fp32 master state, params written as the low-precision cast.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import (FusedOptimizerBase, master_copy,
                                       zeros_like_f32)
from apex_tpu.optimizers.functional import lamb_update


class FusedMixedPrecisionLamb(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float = 1e-3, step: int = 0,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 amsgrad: bool = False, grad_averaging: bool = True,
                 max_grad_norm: float = 1.0, use_nvlamb: bool = False,
                 reduced_precision_dtype=jnp.bfloat16):
        if amsgrad:
            raise RuntimeError(
                "FusedMixedPrecisionLamb does not support the AMSGrad variant.")
        super().__init__(params, lr)
        self._step = jnp.asarray(step, jnp.int32)
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.reduced_precision_dtype = reduced_precision_dtype
        # model params live in reduced precision; state + master in fp32
        self._params = jax.tree_util.tree_map(
            lambda p: p.astype(reduced_precision_dtype), params)
        self.state = {
            "m": zeros_like_f32(params),
            "v": zeros_like_f32(params),
            "master": master_copy(params),
        }

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        pm, m, v, gnorm = lamb_update(
            state["master"], grads, state["m"], state["v"], step=step, lr=lr,
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay,
            bias_correction=self.bias_correction,
            grad_averaging=self.grad_averaging,
            max_grad_norm=self.max_grad_norm, use_nvlamb=self.use_nvlamb,
            inv_scale=inv_scale, found_inf=found_inf)
        p = jax.tree_util.tree_map(
            lambda x: x.astype(self.reduced_precision_dtype), pm)
        return p, {"m": m, "v": v, "master": pm}
