"""FusedAdam — TPU equivalent of ``apex/optimizers/fused_adam.py`` (:146 step).

Implements Adam/AdamW with: ``adam_w_mode`` (multi_tensor_adam.cu:16-19),
``bias_correction``, optional fp32 ``master_weights`` for low-precision params
(fused_adam.py:104-115), capturable semantics by construction (everything is
traced, :234-308), and a ``found_inf``/``inv_scale`` no-op channel replacing the
GradScaler/noop_flag plumbing.

Two execution paths:
- tree path (default): leaf-wise fused update, XLA fuses the elementwise chains
  (see optimizers/functional.py:adam_update).
- flat Pallas path (``use_flat=True``): params/grads/state packed into one
  contiguous 128-lane-aligned buffer per dtype group and updated by the single
  Pallas kernel in ops/pallas/fused_adam_kernel.py — the analog of one
  multi_tensor_apply launch over the whole parameter list, and the layout the
  distributed optimizers shard.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import (FusedOptimizerBase, master_copy,
                                       zeros_like_f32)
from apex_tpu.optimizers.functional import adam_update
from apex_tpu.ops.pallas.fused_adam_kernel import (ADAM_MODE_ADAMW,
                                                   ADAM_MODE_L2,
                                                   fused_adam_flat)
from apex_tpu.utils.flatten import flat_spec, flatten, unflatten


class FusedAdam(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-8, adam_w_mode: bool = True,
                 weight_decay: float = 0.0, amsgrad: bool = False,
                 capturable: bool = True, master_weights: bool = False,
                 use_flat: bool = True):
        if amsgrad:
            # parity with the reference: fused_adam.py:124 raises the same way
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        super().__init__(params, lr)
        del capturable  # always-on under jit; kept for signature parity
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.master_weights = master_weights
        self.use_flat = use_flat

        if use_flat:
            # pack params into one flat fp32-state buffer (Pallas path)
            self._spec = flat_spec(params)
            self._flat_p = flatten(params, self._spec,
                                   dtype=jnp.float32 if master_weights
                                   else None, pad_to=1024)
            self.state = {
                "m": jnp.zeros_like(self._flat_p, dtype=jnp.float32),
                "v": jnp.zeros_like(self._flat_p, dtype=jnp.float32),
            }
            if master_weights:
                # the O2 contract: fp32 masters visible at state["master"]
                # (here the flat buffer itself — fp32, checkpointed)
                self.state["master"] = self._flat_p
        else:
            self.state = {
                "m": zeros_like_f32(params),
                "v": zeros_like_f32(params),
            }
            if master_weights:
                self.state["master"] = master_copy(params)

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        out = adam_update(
            params, grads, state["m"], state["v"], step=step, lr=lr,
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay, adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction, inv_scale=inv_scale,
            found_inf=found_inf, master=state.get("master"))
        if self.master_weights:
            p, m, v, mst = out
            return p, {"m": m, "v": v, "master": mst}
        p, m, v = out
        return p, {"m": m, "v": v}

    def step(self, grads: Any, lr: Optional[float] = None,
             inv_scale=1.0, found_inf=False):
        if not self.use_flat:
            return super().step(grads, lr=lr, inv_scale=inv_scale,
                                found_inf=found_inf)
        # flat Pallas path; step only advances on applied (non-overflow) steps
        self._step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        flat_g = flatten(grads, self._spec, dtype=self._flat_p.dtype,
                         pad_to=self._flat_p.size)
        mode = ADAM_MODE_ADAMW if self.adam_w_mode else ADAM_MODE_L2
        p, m, v = fused_adam_flat(
            self._flat_p, flat_g, self.state["m"], self.state["v"],
            lr=jnp.asarray(self._lr if lr is None else lr, jnp.float32),
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay, step=self._step, mode=mode,
            bias_correction=self.bias_correction, inv_scale=inv_scale,
            found_inf=found_inf)
        self._flat_p, self.state["m"], self.state["v"] = p, m, v
        if self.master_weights:
            self.state["master"] = self._flat_p
        self._params = unflatten(p, self._spec)
        return self._params

    @property
    def master_parameters(self):
        """fp32 master weights (flat path: uncast views of the flat buffer;
        tree path: the ``state['master']`` tree)."""
        if self.use_flat and self.master_weights:
            return unflatten(self._flat_p, self._spec, cast=False)
        return self.state.get("master")

    def set_parameters(self, params):
        super().set_parameters(params)
        if self.use_flat:
            self._flat_p = flatten(params, self._spec,
                                   dtype=self._flat_p.dtype, pad_to=1024)
        if self.master_weights and "master" in self.state:
            import jax as _jax
            self.state["master"] = _jax.tree_util.tree_map(
                lambda p: p.astype(jnp.float32), params)

    def state_dict(self):
        sd = super().state_dict()
        if self.use_flat and self.master_weights:
            # the flat fp32 master is NOT derivable from low-precision params
            import numpy as np
            sd["flat_p"] = np.asarray(self._flat_p)
        return sd

    def load_state_dict(self, sd):
        super().load_state_dict(sd)
        if self.use_flat:
            if "flat_p" in sd:
                self._flat_p = jnp.asarray(sd["flat_p"])
            else:
                # checkpoint from the tree path: rebuild the flat buffer
                self._flat_p = flatten(self._params, self._spec,
                                       dtype=self._flat_p.dtype,
                                       pad_to=1024)
            if not isinstance(self.state["m"], jax.Array):
                # tree-path (pre-flip default) checkpoint: repack m/v; a
                # tree master becomes the flat fp32 buffer (keeps the O2
                # precision the low-precision params can't reconstruct)
                if "master" in self.state:
                    self._flat_p = flatten(self.state["master"], self._spec,
                                           dtype=jnp.float32, pad_to=1024)
                self.state = {
                    "m": flatten(self.state["m"], self._spec,
                                 dtype=jnp.float32, pad_to=1024),
                    "v": flatten(self.state["v"], self._spec,
                                 dtype=jnp.float32, pad_to=1024),
                }
            if self.master_weights:
                self.state["master"] = self._flat_p


class FusedAdamW(FusedAdam):
    """Convenience alias with decoupled weight decay on by default."""

    def __init__(self, params, lr: float = 1e-3, **kw):
        kw.setdefault("adam_w_mode", True)
        super().__init__(params, lr=lr, **kw)
