"""DistributedFusedLAMB — ZeRO-sharded LAMB, TPU-native.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py`` (1333 LoC) —
the MLPerf-BERT optimizer: block/chunk/shard flat partition (``_flat_split``
:444), full-all-reduce or reduce-scatter+all-reduce grad modes (:845,:903),
fused global L2 norm, ``set_is_accumulation_step`` (:787), clip-after-AR,
NCCL premul-sum scaling (:19-23).

TPU design: same sharded-flat-state layout as DistributedFusedAdam; the LAMB
specifics on top:
- global grad-norm clip from one fused L2 over the sharded grad buffer
  (psum of shard partials ≡ the reference's premul-sum + AR norm);
- per-TENSOR trust ratios need tensor-boundary norms, which the flat shard
  doesn't respect — so the update term is all-gathered (this replaces the
  param all-gather; same bytes) and the trust-ratio scaling happens on whole
  tensors, exactly the reference's two-phase structure
  (multi_tensor_lamb_compute_update_term → update_weights,
  apex/contrib/csrc/optimizers/multi_tensor_distopt_lamb.cpp:18-21).
- ``set_is_accumulation_step`` maps to simply not calling step() during
  accumulation (grad accumulation is a jnp add in the user loop).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.utils.flatten import flat_spec, flatten, unflatten

_f32 = jnp.float32


class DistributedFusedLAMB:
    def __init__(self, params: Any, mesh: Mesh, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 max_grad_norm: float = 1.0, adam_w_mode: bool = True,
                 grad_averaging: bool = True, use_nvlamb: bool = False,
                 axis: str = "data", state_dtype=jnp.float32,
                 clip_after_ar: bool = True, **_compat):
        self.mesh = mesh
        self.axis = axis
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.max_grad_norm = max_grad_norm
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        self.clip_after_ar = clip_after_ar

        world = mesh.shape[axis]
        self._spec = flat_spec(params)
        flat_p = flatten(params, self._spec, dtype=_f32, pad_to=1024 * world)
        self._n = flat_p.size
        shard = NamedSharding(mesh, P(axis))
        self._shard = shard
        self._rep = NamedSharding(mesh, P())
        self._master = jax.device_put(flat_p, shard)
        self._m = jax.device_put(jnp.zeros((self._n,), state_dtype), shard)
        self._v = jax.device_put(jnp.zeros((self._n,), state_dtype), shard)
        self._params = params
        self._step = jnp.zeros((), jnp.int32)
        self._is_accumulation_step = False
        self._jit = None

    def set_is_accumulation_step(self, flag: bool):
        """Parity with :787 — when True, step() is a no-op (caller keeps
        accumulating grads)."""
        self._is_accumulation_step = flag

    def _build(self):
        spec = self._spec
        shard_s, rep_s = self._shard, self._rep
        beta1, beta2 = self.betas
        eps, wd = self.eps, self.weight_decay
        n = self._n
        max_gn = self.max_grad_norm
        bias_corr = self.bias_correction
        grad_avg = self.grad_averaging
        adam_w = self.adam_w_mode
        use_nvlamb = self.use_nvlamb

        def step_fn(p32, m, v, grads, step, lr, inv_scale, found_inf):
            flat_g = flatten(grads, spec, dtype=_f32, pad_to=n)
            flat_g = jax.lax.with_sharding_constraint(flat_g, shard_s)
            g32 = flat_g * inv_scale

            # fused global grad norm + clip (padding is zero ⇒ exact)
            gnorm = jnp.sqrt(jnp.sum(g32 * g32))
            clip = jnp.maximum(gnorm / max_gn, 1.0) if max_gn else _f32(1.0)
            g32 = g32 / clip

            if not adam_w:
                g32 = g32 + wd * p32
            beta3 = 1.0 - beta1 if grad_avg else 1.0
            m_new = beta1 * m.astype(_f32) + beta3 * g32
            v_new = beta2 * v.astype(_f32) + (1 - beta2) * g32 * g32
            stepf = step.astype(_f32)
            if bias_corr:
                bc1 = 1 - jnp.power(_f32(beta1), stepf)
                bc2 = 1 - jnp.power(_f32(beta2), stepf)
            else:
                bc1 = bc2 = _f32(1.0)
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if adam_w and wd != 0.0:
                upd = upd + wd * p32

            # phase 2: per-tensor trust ratio on whole tensors — gather the
            # update term (replaces the param all-gather; same payload)
            upd_full = jax.lax.with_sharding_constraint(upd, rep_s)
            p_full = jax.lax.with_sharding_constraint(p32, rep_s)
            upd_tree = unflatten(upd_full, spec)
            p_tree = unflatten(p_full, spec)

            def trust(pt, ut):
                w_norm = jnp.sqrt(jnp.sum(pt.astype(_f32) ** 2))
                u_norm = jnp.sqrt(jnp.sum(ut.astype(_f32) ** 2))
                if use_nvlamb:
                    r = jnp.where(u_norm > 0, w_norm / u_norm, 1.0)
                else:
                    r = jnp.where((w_norm > 0) & (u_norm > 0),
                                  w_norm / u_norm, 1.0)
                return (pt.astype(_f32) - lr * r * ut.astype(_f32))

            new_tree = jax.tree_util.tree_map(trust, p_tree, upd_tree)
            flat_new = flatten(new_tree, spec, dtype=_f32, pad_to=n)
            keep = found_inf
            flat_new = jnp.where(keep, p_full, flat_new)
            p_out = jax.lax.with_sharding_constraint(flat_new, shard_s)
            m_out = jax.lax.with_sharding_constraint(
                jnp.where(keep, m.astype(_f32), m_new).astype(m.dtype),
                shard_s)
            v_out = jax.lax.with_sharding_constraint(
                jnp.where(keep, v.astype(_f32), v_new).astype(v.dtype),
                shard_s)
            params_out = unflatten(
                jax.lax.with_sharding_constraint(flat_new, rep_s), spec)
            return p_out, m_out, v_out, params_out, gnorm

        return jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def step(self, grads: Any, lr: Optional[float] = None, inv_scale=1.0,
             found_inf=False):
        if self._is_accumulation_step:
            return self._params
        if self._jit is None:
            self._jit = self._build()
        self._step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        with self.mesh:
            self._master, self._m, self._v, params, gnorm = self._jit(
                self._master, self._m, self._v, grads, self._step,
                jnp.asarray(self.lr if lr is None else lr, _f32),
                jnp.asarray(inv_scale, _f32),
                jnp.asarray(found_inf, jnp.bool_))
        self._params = params
        self.last_grad_norm = gnorm
        return params

    @property
    def parameters(self):
        return self._params

    def set_parameters(self, params: Any):
        self._params = params
        self._master = jax.device_put(
            flatten(params, self._spec, dtype=_f32, pad_to=self._n),
            self._shard)

    def state_dict(self):
        return {"step": int(self._step), "lr": self.lr,
                "master": np.asarray(self._master),
                "m": np.asarray(self._m), "v": np.asarray(self._v)}

    def load_state_dict(self, sd):
        self._step = jnp.asarray(sd["step"], jnp.int32)
        self.lr = sd.get("lr", self.lr)
        self._master = jax.device_put(jnp.asarray(sd["master"]), self._shard)
        self._m = jax.device_put(jnp.asarray(sd["m"]), self._shard)
        self._v = jax.device_put(jnp.asarray(sd["v"]), self._shard)
        self._params = unflatten(self._master, self._spec)
        self._jit = None
