"""DistributedFusedLAMB — ZeRO-sharded LAMB, TPU-native.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py`` (1333 LoC) —
the MLPerf-BERT optimizer: block/chunk/shard flat partition (``_flat_split``
:444), full-all-reduce or reduce-scatter+all-reduce grad modes (:845,:903),
fused global L2 norm, ``set_is_accumulation_step`` (:787), clip-after-AR,
NCCL premul-sum scaling (:19-23).

TPU design: same sharded-flat-state layout as DistributedFusedAdam; the LAMB
specifics on top:
- global grad-norm clip from one fused L2 over the sharded grad buffer
  (psum of shard partials ≡ the reference's premul-sum + AR norm);
- per-TENSOR trust ratios need tensor-boundary norms, which the flat shard
  doesn't respect — so the update term is all-gathered (this replaces the
  param all-gather; same bytes) and the trust-ratio scaling happens on whole
  tensors, exactly the reference's two-phase structure
  (multi_tensor_lamb_compute_update_term → update_weights,
  apex/contrib/csrc/optimizers/multi_tensor_distopt_lamb.cpp:18-21).

Grad-sync modes (reference :845 ``_full_all_reduce`` vs :903
``_reduce_scatter_and_all_reduce``): under GSPMD the collective pattern is
chosen by the sharding constraint placed on the flat grad buffer before the
moment update —
- ``full_ar=True``: the grad buffer is constrained REPLICATED (XLA emits an
  all-reduce-shaped sync; every device holds the full gradient) and each
  device then slices its own state shard locally — the reference's
  single-node DGX mode, which trades bandwidth for one fewer collective
  hop on the update path.
- ``full_ar=False`` (default): the grad buffer is constrained to the
  1-D shard (XLA emits reduce-scatter-shaped resharding; each device
  materializes only grad-shard bytes) — the reference's multi-node mode.
Both are numerically identical (tests assert this), they differ only in
which collectives the compiled module contains.

Clip point (reference :818/:944 vs :976-996, selected by ``clip_after_ar``):
- ``clip_after_ar=True`` (default): one global L2 norm of the synced flat
  gradient, clip by ``max_grad_norm`` — the reference's post-all-reduce
  clip (:944-975, kernel-side via ``max_grad_norm * clip_after_ar`` :1073).
- ``clip_after_ar=False``: the reference clips each rank's gradient by
  ONE coefficient from a norm computed BEFORE the sync (:981-996) so the
  clip never waits on a collective. Two TPU realizations, by grad-sync
  mode:
  - ``full_ar=True``: grads are replicated, so the reference's exact
    semantics (one uniform coefficient from the device-local
    full-gradient norm) is free — local math over replicated data.
  - ``full_ar=False`` (RS+AR): the pre-sync view is the device's 1-D
    flat shard; each shard is clipped by its own shard-local norm,
    keeping the coefficient collective-free. This is a documented
    TRANSLATION (per-shard coefficients depend on flat-shard boundaries
    and world size), not numerics parity — numerics tests pin all three
    behaviors.
- ``fused_norm`` (:119,:176) only applies when clipping pre-AR (the norm
  fuses into the scale pass); here the local-shard norm IS emitted inside
  the single jitted step (XLA fuses it), so the kwarg selects dispatched
  behavior exactly when the reference's does. ``fuse_scale`` (:171): the
  ``inv_scale`` multiply is always fused into the step; accepted for API
  parity and validated, not dispatched.
- ``set_is_accumulation_step(True)`` (:787) makes step() ACCUMULATE: grads
  are added into a sharded flat accumulation buffer (shard-local adds; under
  GSPMD grad-sum placement belongs to the caller's backward) and the next
  real step folds the buffer in and zeros it — the reference's
  skip-sync-while-accumulating flow, with the flag actually gating state.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.utils.flatten import flat_spec, flatten, unflatten

_f32 = jnp.float32


class DistributedFusedLAMB:
    def __init__(self, params: Any, mesh: Mesh, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 max_grad_norm: float = 1.0, adam_w_mode: bool = True,
                 grad_averaging: bool = True, use_nvlamb: bool = False,
                 axis: str = "data", state_dtype=jnp.float32,
                 clip_after_ar: bool = True, full_ar: bool = False,
                 fused_norm: bool = True, fuse_scale: bool = True,
                 abstract_state: bool = False, **_compat):
        self.mesh = mesh
        self.axis = axis
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.max_grad_norm = max_grad_norm
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        self.clip_after_ar = clip_after_ar
        self.full_ar = full_ar
        # reference :176 — fused_norm only applies when clipping pre-AR
        self.fused_norm = fused_norm if not clip_after_ar else False
        self.fuse_scale = fuse_scale

        world = mesh.shape[axis]
        self._spec = flat_spec(params)
        flat_p = flatten(params, self._spec, dtype=_f32, pad_to=1024 * world)
        self._n = flat_p.size
        shard = NamedSharding(mesh, P(axis))
        self._shard = shard
        self._rep = NamedSharding(mesh, P())
        from apex_tpu.optimizers.distributed_fused_adam import _state_put

        put = _state_put(abstract_state)
        self.abstract_state = abstract_state
        self._master = put(flat_p, shard)
        self._m = put(jnp.zeros((self._n,), state_dtype), shard)
        self._v = put(jnp.zeros((self._n,), state_dtype), shard)
        self._params = params
        self._step = jnp.zeros((), jnp.int32)
        self._is_accumulation_step = False
        self._acc = None  # sharded flat grad-accumulation buffer
        self._jit = None
        self._jit_acc = None

    def set_is_accumulation_step(self, flag: bool):
        """Parity with :787 — while True, step() accumulates grads into the
        sharded flat buffer instead of updating; the next real step folds
        the buffer in."""
        self._is_accumulation_step = flag

    def _accumulate(self, grads, inv_scale, found_inf):
        """Add ``grads * inv_scale`` into the sharded buffer; a found_inf
        microbatch contributes NOTHING (the reference skips overflowed
        microbatches rather than poisoning the accumulator)."""
        if self._jit_acc is None:
            spec, n, shard_s = self._spec, self._n, self._shard

            def acc_fn(acc, grads, inv_scale, found_inf):
                flat_g = flatten(grads, spec, dtype=_f32, pad_to=n)
                flat_g = jax.lax.with_sharding_constraint(flat_g, shard_s)
                # gate the PRODUCT: inv_scale·inf would make 0·inf = NaN
                return acc + jnp.where(found_inf, 0.0, inv_scale * flat_g)

            self._jit_acc = jax.jit(acc_fn, donate_argnums=(0,))
        if self._acc is None:
            self._acc = jax.device_put(jnp.zeros((self._n,), _f32),
                                       self._shard)
        with self.mesh:
            self._acc = self._jit_acc(self._acc, grads,
                                      jnp.asarray(inv_scale, _f32),
                                      jnp.asarray(found_inf, jnp.bool_))

    def _build(self):
        spec = self._spec
        shard_s, rep_s = self._shard, self._rep
        beta1, beta2 = self.betas
        eps, wd = self.eps, self.weight_decay
        n = self._n
        max_gn = self.max_grad_norm
        bias_corr = self.bias_correction
        grad_avg = self.grad_averaging
        adam_w = self.adam_w_mode
        use_nvlamb = self.use_nvlamb

        # grad-sync mode (reference :845 vs :903): the constraint on the
        # flat grad buffer picks the collective pattern XLA compiles —
        # replicated ⇒ all-reduce-shaped (full_ar), sharded ⇒
        # reduce-scatter-shaped (RS+AR). Numerics are identical.
        grad_sharding = rep_s if self.full_ar else shard_s
        clip_after_ar = self.clip_after_ar
        world = self.mesh.shape[self.axis]
        # row i of the (world, n/world) view IS device i's flat shard
        row_s = NamedSharding(self.mesh, P(self.axis, None))

        def step_fn(p32, m, v, grads, acc, step, lr, inv_scale, found_inf):
            flat_g = flatten(grads, spec, dtype=_f32, pad_to=n)
            flat_g = jax.lax.with_sharding_constraint(flat_g, grad_sharding)
            g32 = flat_g * inv_scale
            if acc is not None:  # fold in accumulated grads (:787 flow) —
                # the buffer is already unscaled (per-microbatch inv_scale
                # applied at accumulation time)
                g32 = g32 + jax.lax.with_sharding_constraint(
                    acc, grad_sharding)

            if clip_after_ar or not max_gn:
                # fused global grad norm + clip (padding is zero ⇒ exact)
                gnorm = jnp.sqrt(jnp.sum(g32 * g32))
                clip = (jnp.maximum(gnorm / max_gn, 1.0) if max_gn
                        else _f32(1.0))
                g32 = g32 / clip
            elif self.full_ar:
                # pre-AR clip, full-AR mode: every device already holds
                # the FULL gradient (replicated constraint), so the
                # reference's exact semantics — ONE coefficient from the
                # device-local full-gradient norm (:983-996), applied
                # uniformly — costs no collective here: the norm is local
                # math over replicated data (fused_norm dispatched)
                gnorm = jnp.sqrt(jnp.sum(g32 * g32))
                coeff = jnp.minimum(max_gn / (1e-6 + gnorm), 1.0)
                g32 = g32 * coeff
            else:
                # pre-AR clip, sharded (RS+AR) mode: the pre-sync view of
                # the flat buffer is the device's own 1-D shard, so each
                # shard is clipped by its shard-local norm — the (world,·)
                # rows coincide with the P(axis) shards, keeping the clip
                # coefficient collective-free (the property this mode
                # exists for). NOTE this is a deliberate TRANSLATION, not
                # numerics parity: the reference clips with one uniform
                # coefficient per rank, so here the clipped gradient
                # depends on flat-shard boundaries (and hence world size);
                # use full_ar=True with clip_after_ar=False for the
                # reference-exact pre-AR coefficient.
                gsh = jax.lax.with_sharding_constraint(
                    g32.reshape(world, n // world), row_s)
                local = jnp.sqrt(jnp.sum(gsh * gsh, axis=1, keepdims=True))
                coeff = jnp.minimum(max_gn / (1e-6 + local), 1.0)
                g32 = (gsh * coeff).reshape(n)
                g32 = jax.lax.with_sharding_constraint(g32, grad_sharding)
                # reported norm stays the true global pre-clip norm
                gnorm = jnp.sqrt(jnp.sum(local * local))

            if not adam_w:
                g32 = g32 + wd * p32
            beta3 = 1.0 - beta1 if grad_avg else 1.0
            m_new = beta1 * m.astype(_f32) + beta3 * g32
            v_new = beta2 * v.astype(_f32) + (1 - beta2) * g32 * g32
            stepf = step.astype(_f32)
            if bias_corr:
                bc1 = 1 - jnp.power(_f32(beta1), stepf)
                bc2 = 1 - jnp.power(_f32(beta2), stepf)
            else:
                bc1 = bc2 = _f32(1.0)
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if adam_w and wd != 0.0:
                upd = upd + wd * p32

            # phase 2: per-tensor trust ratio on whole tensors — gather the
            # update term (replaces the param all-gather; same payload)
            upd_full = jax.lax.with_sharding_constraint(upd, rep_s)
            p_full = jax.lax.with_sharding_constraint(p32, rep_s)
            upd_tree = unflatten(upd_full, spec)
            p_tree = unflatten(p_full, spec)

            def trust(pt, ut):
                w_norm = jnp.sqrt(jnp.sum(pt.astype(_f32) ** 2))
                u_norm = jnp.sqrt(jnp.sum(ut.astype(_f32) ** 2))
                if use_nvlamb:
                    r = jnp.where(u_norm > 0, w_norm / u_norm, 1.0)
                else:
                    r = jnp.where((w_norm > 0) & (u_norm > 0),
                                  w_norm / u_norm, 1.0)
                return (pt.astype(_f32) - lr * r * ut.astype(_f32))

            new_tree = jax.tree_util.tree_map(trust, p_tree, upd_tree)
            flat_new = flatten(new_tree, spec, dtype=_f32, pad_to=n)
            keep = found_inf
            flat_new = jnp.where(keep, p_full, flat_new)
            p_out = jax.lax.with_sharding_constraint(flat_new, shard_s)
            m_out = jax.lax.with_sharding_constraint(
                jnp.where(keep, m.astype(_f32), m_new).astype(m.dtype),
                shard_s)
            v_out = jax.lax.with_sharding_constraint(
                jnp.where(keep, v.astype(_f32), v_new).astype(v.dtype),
                shard_s)
            params_out = unflatten(
                jax.lax.with_sharding_constraint(flat_new, rep_s), spec)
            return p_out, m_out, v_out, params_out, gnorm

        return jax.jit(step_fn, donate_argnums=(0, 1, 2, 4))

    def _check_concrete(self, what: str):
        if self.abstract_state:
            raise RuntimeError(
                f"{what} requires runtime state, but this instance was "
                "built with abstract_state=True (compile-only: state is "
                "shape structs for AOT lowering, tools/stack_aot.py)")

    def step(self, grads: Any, lr: Optional[float] = None, inv_scale=1.0,
             found_inf=False):
        self._check_concrete("step()")
        if self._is_accumulation_step:
            self._accumulate(grads, inv_scale, found_inf)
            return self._params
        if self._jit is None:
            self._jit = self._build()
        self._step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        with self.mesh:
            self._master, self._m, self._v, params, gnorm = self._jit(
                self._master, self._m, self._v, grads, self._acc,
                self._step,
                jnp.asarray(self.lr if lr is None else lr, _f32),
                jnp.asarray(inv_scale, _f32),
                jnp.asarray(found_inf, jnp.bool_))
        self._acc = None  # buffer donated & consumed by the step
        self._params = params
        self.last_grad_norm = gnorm
        return params

    @property
    def parameters(self):
        return self._params

    def set_parameters(self, params: Any):
        self._params = params
        self._master = jax.device_put(
            flatten(params, self._spec, dtype=_f32, pad_to=self._n),
            self._shard)

    def state_dict(self):
        self._check_concrete("state_dict()")
        return {"step": int(self._step), "lr": self.lr,
                "master": np.asarray(self._master),
                "m": np.asarray(self._m), "v": np.asarray(self._v),
                "acc": (None if self._acc is None
                        else np.asarray(self._acc))}

    def load_state_dict(self, sd):
        self._check_concrete("load_state_dict()")
        self._step = jnp.asarray(sd["step"], jnp.int32)
        self.lr = sd.get("lr", self.lr)
        self._master = jax.device_put(jnp.asarray(sd["master"]), self._shard)
        self._m = jax.device_put(jnp.asarray(sd["m"]), self._shard)
        self._v = jax.device_put(jnp.asarray(sd["v"]), self._shard)
        acc = sd.get("acc")
        self._acc = (None if acc is None else
                     jax.device_put(jnp.asarray(acc), self._shard))
        self._params = unflatten(self._master, self._spec)
        self._jit = None
