"""DistributedFusedAdam — ZeRO-2 optimizer-state sharding, TPU-native.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py`` (3488 LoC) —
params flattened into buckets, optimizer state sharded over
``distributed_process_group`` and optionally replicated over an orthogonal
``redundant_process_group`` (2D grid, :316-328); overlapped reduce-scatter grad
sync via backward hooks (:1877) and all-gather param sync via forward hooks
(:915-938); dtype-flexible state incl. bf16-param + 16-bit-remainder
reconstruction (:2611); checkpoint v1 gather-on-root (:2907) / v2 sharded
(:3059-3329).

TPU design (SURVEY §2.5 mapping): the bucket/fragment bookkeeping
(``ParameterFragment`` :389-414) collapses into ONE 128-lane-aligned flat
buffer per optimizer, padded to the shard grid; the optimizer state carries a
``NamedSharding`` over the data axis and the update runs under jit with
sharding constraints — XLA lowers the grad flatten→constraint into a
reduce-scatter and the param constraint into an all-gather, overlapping both
with neighboring compute (the role of the reference's hook+stream machinery).
The fused Adam math itself is the same update as ops/pallas/fused_adam_kernel
(jnp form here so GSPMD can shard it freely).

``store_param_remainders``: bf16 master + int16 mantissa remainder, exact fp32
reconstruction via bit ops (reference :2611 semantics) — halves master-weight
memory with zero precision loss.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.multi_tensor.functional import multi_tensor_l2norm
from apex_tpu.utils.flatten import FlatSpec, flat_spec, flatten, unflatten

_f32 = jnp.float32


def _split_f32(x32: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """fp32 → (bf16 high bits, int16 low bits) — exact decomposition."""
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    hi = jax.lax.bitcast_convert_type(
        (bits >> 16).astype(jnp.uint16), jnp.bfloat16)
    lo = (bits & jnp.uint32(0xFFFF)).astype(jnp.uint16)
    return hi, lo


def _join_f32(hi: jax.Array, lo: jax.Array) -> jax.Array:
    bits = (jax.lax.bitcast_convert_type(hi, jnp.uint16)
            .astype(jnp.uint32) << 16) | lo.astype(jnp.uint32)
    return jax.lax.bitcast_convert_type(bits, _f32)


class DistributedFusedAdam:
    """ZeRO-2 Adam over a mesh data axis.

    Usage::

        mesh = get_mesh("data")
        opt = DistributedFusedAdam(params, mesh, lr=1e-3)
        params = opt.step(grads)          # grads: one (already-summed or
                                          # per-host identical) pytree

    Under jit the step is: flatten grads → reduce-scatter (via sharding
    constraint) → sharded fused Adam on the state shards → all-gather params.
    ``grad_sync_dtype`` lowers the reduce-scatter payload (bf16 grads ride a
    half-width collective, reference ``grad_sync_dtype``).
    """

    def __init__(self, params: Any, mesh: Mesh, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-8, adam_w_mode: bool = True,
                 weight_decay: float = 0.0, axis: str = "data",
                 redundant_axis: Optional[str] = None,  # 2D grid: pass a 2D
                 # mesh (axis, redundant_axis); P(axis) shardings replicate
                 # the state across the redundant axis automatically — the
                 # reference's shard × replica process grid (:316-328)
                 state_dtype=jnp.float32, grad_sync_dtype=None,
                 store_param_remainders: bool = False,
                 overlap_grad_sync: bool = True,
                 overlap_param_sync: bool = True,
                 bucket_cap_mb: int = 100, pipeline_size: int = 2,
                 **_compat):
        # overlap_*/bucket_cap/pipeline knobs: XLA's latency-hiding scheduler
        # owns these on TPU; accepted for API parity.
        self.mesh = mesh
        self.axis = axis
        self.redundant_axis = redundant_axis  # state replicated over it
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.state_dtype = state_dtype
        self.grad_sync_dtype = grad_sync_dtype
        self.store_param_remainders = store_param_remainders

        if redundant_axis is not None and \
                redundant_axis not in mesh.axis_names:
            raise ValueError(
                f"redundant_axis {redundant_axis!r} is not a mesh axis "
                f"{mesh.axis_names}; pass a 2D mesh (axis, redundant_axis) "
                "to get state replication over the redundant group")
        world = mesh.shape[axis]
        self._spec = flat_spec(params)
        pad = 1024 * world
        flat_p = flatten(params, self._spec, dtype=_f32, pad_to=pad)
        self._n = flat_p.size

        shard = NamedSharding(mesh, P(axis))
        rep = NamedSharding(mesh, P())
        self._shard, self._rep = shard, rep

        if store_param_remainders:
            hi, lo = _split_f32(flat_p)
            self._master_hi = jax.device_put(hi, shard)
            self._master_lo = jax.device_put(lo, shard)
        else:
            self._master = jax.device_put(flat_p, shard)
        self._m = jax.device_put(jnp.zeros((self._n,), state_dtype), shard)
        self._v = jax.device_put(jnp.zeros((self._n,), state_dtype), shard)
        self._params = params
        self._step = jnp.zeros((), jnp.int32)
        self._jit_step = None

    # ------------------------------------------------------------------ step
    def _build_step(self):
        spec = self._spec
        axis = self.axis
        shard_s, rep_s = self._shard, self._rep
        beta1, beta2 = self.betas
        eps, wd = self.eps, self.weight_decay
        adam_w, bias_corr = self.adam_w_mode, self.bias_correction
        gdt = self.grad_sync_dtype
        remainders = self.store_param_remainders
        n = self._n

        def step_fn(master_parts, m, v, grads, step, lr, inv_scale,
                    found_inf):
            flat_g = flatten(grads, spec, dtype=gdt or _f32, pad_to=n)
            # ZeRO reduce-scatter point: constrain the grad buffer to the
            # shard layout; XLA emits reduce-scatter when producers are
            # replicated/partial
            flat_g = jax.lax.with_sharding_constraint(flat_g, shard_s)
            g32 = flat_g.astype(_f32) * inv_scale

            if remainders:
                hi, lo = master_parts
                p32 = _join_f32(hi, lo)
            else:
                (p32,) = master_parts
                p32 = p32.astype(_f32)

            if not adam_w:
                g32 = g32 + wd * p32
            m32 = m.astype(_f32)
            v32 = v.astype(_f32)
            m_new = beta1 * m32 + (1 - beta1) * g32
            v_new = beta2 * v32 + (1 - beta2) * g32 * g32
            stepf = step.astype(_f32)
            if bias_corr:
                bc1 = 1 - jnp.power(_f32(beta1), stepf)
                bc2 = 1 - jnp.power(_f32(beta2), stepf)
            else:
                bc1 = bc2 = _f32(1.0)
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if adam_w:
                upd = upd + wd * p32
            p_new = p32 - lr * upd

            keep = found_inf
            p_new = jnp.where(keep, p32, p_new)
            # state outputs stay in the shard layout (ZeRO memory win)
            p_new = jax.lax.with_sharding_constraint(p_new, shard_s)
            m_out = jax.lax.with_sharding_constraint(
                jnp.where(keep, m32, m_new).astype(m.dtype), shard_s)
            v_out = jax.lax.with_sharding_constraint(
                jnp.where(keep, v32, v_new).astype(v.dtype), shard_s)

            # ZeRO all-gather point: params replicated for the next forward
            full = jax.lax.with_sharding_constraint(p_new, rep_s)
            params_out = unflatten(full, spec)

            if remainders:
                hi_new, lo_new = _split_f32(p_new)
                return (hi_new, lo_new), m_out, v_out, params_out
            return (p_new,), m_out, v_out, params_out

        return jax.jit(step_fn, donate_argnums=(0, 1, 2))

    def step(self, grads: Any, lr: Optional[float] = None, inv_scale=1.0,
             found_inf=False):
        if self._jit_step is None:
            self._jit_step = self._build_step()
        self._step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        master_parts = ((self._master_hi, self._master_lo)
                        if self.store_param_remainders else (self._master,))
        with self.mesh:
            master_parts, self._m, self._v, params = self._jit_step(
                master_parts, self._m, self._v, grads, self._step,
                jnp.asarray(self.lr if lr is None else lr, _f32),
                jnp.asarray(inv_scale, _f32),
                jnp.asarray(found_inf, jnp.bool_))
        if self.store_param_remainders:
            self._master_hi, self._master_lo = master_parts
        else:
            (self._master,) = master_parts
        self._params = params
        return params

    # ------------------------------------------------------------- utilities
    @property
    def parameters(self):
        return self._params

    def set_parameters(self, params: Any):
        """Overwrite params AND the sharded fp32 master (e.g. after ASP
        masking) so the source-of-truth flat buffer stays consistent."""
        self._params = params
        flat = flatten(params, self._spec, dtype=_f32, pad_to=self._n)
        if self.store_param_remainders:
            hi, lo = _split_f32(flat)
            self._master_hi = jax.device_put(hi, self._shard)
            self._master_lo = jax.device_put(lo, self._shard)
        else:
            self._master = jax.device_put(flat, self._shard)

    def grad_norm(self, grads) -> jax.Array:
        """Global L2 grad norm (ref ``_local_grad_norm`` + all-reduce :2150)."""
        g, _ = multi_tensor_l2norm(grads)
        return g

    def zero_grad(self, set_to_none: bool = True):
        pass

    # ---------------------------------------------------------- checkpointing
    def state_dict(self, gather_on_root: bool = True):
        """v1 semantics (ref :2907): gather shards → full host arrays."""
        master = (_join_f32(self._master_hi, self._master_lo)
                  if self.store_param_remainders else self._master)
        return {
            "step": int(self._step),
            "lr": self.lr,
            "master": np.asarray(master),
            "m": np.asarray(self._m),
            "v": np.asarray(self._v),
        }

    def sharded_state_dict(self):
        """v2 semantics (ref :3059-3329): per-shard state, no gather. Each
        entry maps shard index → host array; pair with ``flat_spec`` metadata
        for reload on a different world size."""
        world = self.mesh.shape[self.axis]
        shard_size = self._n // world

        def shards(x):
            # key by shard POSITION and dedup: on a 2D (shard × replica)
            # grid each shard index appears once per replica
            out = {}
            for s in x.addressable_shards:
                idx = (s.index[0].start or 0) // shard_size
                if idx not in out:
                    out[idx] = np.asarray(s.data)
            return out

        master = (_join_f32(self._master_hi, self._master_lo)
                  if self.store_param_remainders else self._master)
        return {
            "step": int(self._step),
            "world": world,
            "total_size": self._n,
            "master": shards(master),
            "m": shards(self._m),
            "v": shards(self._v),
        }

    def load_state_dict(self, sd):
        self._step = jnp.asarray(sd["step"], jnp.int32)
        self.lr = sd.get("lr", self.lr)
        if "world" in sd:  # sharded (v2) checkpoint: concatenate shards
            def join(d):
                return np.concatenate([d[i] for i in sorted(d)])

            master = jnp.asarray(join(sd["master"]))
            m = jnp.asarray(join(sd["m"]))
            v = jnp.asarray(join(sd["v"]))
        else:
            master = jnp.asarray(sd["master"])
            m = jnp.asarray(sd["m"])
            v = jnp.asarray(sd["v"])
        if self.store_param_remainders:
            hi, lo = _split_f32(master)
            self._master_hi = jax.device_put(hi, self._shard)
            self._master_lo = jax.device_put(lo, self._shard)
        else:
            self._master = jax.device_put(master, self._shard)
        self._m = jax.device_put(m, self._shard)
        self._v = jax.device_put(v, self._shard)
        self._params = unflatten(master, self._spec)
        self._jit_step = None
