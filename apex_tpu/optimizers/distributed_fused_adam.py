"""DistributedFusedAdam — ZeRO-2 optimizer-state sharding, TPU-native.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py`` (3488 LoC) —
params flattened into buckets, optimizer state sharded over
``distributed_process_group`` and optionally replicated over an orthogonal
``redundant_process_group`` (2D grid, :316-328); overlapped reduce-scatter grad
sync via backward hooks (:1877) and all-gather param sync via forward hooks
(:915-938); dtype-flexible state incl. bf16-param + 16-bit-remainder
reconstruction (:2611); checkpoint v1 gather-on-root (:2907) / v2 sharded
(:3059-3329).

TPU design (SURVEY §2.5 mapping): the bucket/fragment bookkeeping
(``ParameterFragment`` :389-414) collapses into ONE 128-lane-aligned flat
buffer per optimizer, padded to the shard grid; the optimizer state carries a
``NamedSharding`` over the data axis and the update runs under jit with
sharding constraints — XLA lowers the grad flatten→constraint into a
reduce-scatter and the param constraint into an all-gather, overlapping both
with neighboring compute (the role of the reference's hook+stream machinery).
The fused Adam math itself is the same update as ops/pallas/fused_adam_kernel
(jnp form here so GSPMD can shard it freely).

Round-2 depth (VERDICT item 3), matching reference semantics:

- **Param groups** (ref :270+): constructor accepts a list of
  ``{"params": pytree, "lr"/"weight_decay"/"betas"/"eps": ...}`` dicts.
  Groups occupy contiguous ranges of the flat buffer; per-element
  hyperparameters are resolved inside the jitted step from (G,) vectors +
  the static group boundaries (an iota-compare, fused by XLA — no stored
  per-element group-id array).
- **Integrated clip_grad_norm** (ref :2275): ``max_grad_norm`` clips by the
  global norm INSIDE the jitted sharded step (one extra reduction over the
  shard, psum'd by GSPMD); the computed norm is returned with the step.
- **with_scaled_states** (ref :2694, 2834): fp16 optimizer state with
  per-1024-element-block fp32 scale factors — halved state memory with
  per-block dynamic range, the reference's per-fragment scaled-state scheme
  on TPU-friendly fixed blocks.
- **Grad accumulation API**: ``accumulate(grads)`` adds micro-batch grads
  into a sharded flat buffer; ``step()`` without grads consumes and zeroes
  it (the reference's hook-accumulated main-grad buffer flow).
- **World-size resharding**: v2 sharded checkpoints record the unpadded
  payload size; ``load_state_dict`` re-pads to the new mesh's grid so a
  world=8 checkpoint loads on world=4 and vice versa (ref v2 :3059-3329).

``store_param_remainders``: bf16 master + int16 mantissa remainder, exact fp32
reconstruction via bit ops (reference :2611 semantics) — halves master-weight
memory with zero precision loss.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.multi_tensor.functional import multi_tensor_l2norm
from apex_tpu.utils.flatten import flat_spec, flatten, unflatten

_f32 = jnp.float32
_SCALE_BLOCK = 1024  # with_scaled_states: elements per fp32 scale factor
_F16_MAX = 65504.0


def _state_put(abstract: bool):
    """State placement for the ZeRO optimizers: ``jax.device_put``, or —
    for ``abstract_state=True`` compile-only instances — a sharded
    ShapeDtypeStruct builder (no runtime buffers), so the step can be
    AOT-lowered against a deviceless topology mesh (tools/stack_aot.py).
    Shared with DistributedFusedLAMB."""
    if abstract:
        return lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                 sharding=s)
    return jax.device_put


def _split_f32(x32: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """fp32 → (bf16 high bits, int16 low bits) — exact decomposition."""
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    hi = jax.lax.bitcast_convert_type(
        (bits >> 16).astype(jnp.uint16), jnp.bfloat16)
    lo = (bits & jnp.uint32(0xFFFF)).astype(jnp.uint16)
    return hi, lo


def _join_f32(hi: jax.Array, lo: jax.Array) -> jax.Array:
    bits = (jax.lax.bitcast_convert_type(hi, jnp.uint16)
            .astype(jnp.uint32) << 16) | lo.astype(jnp.uint32)
    return jax.lax.bitcast_convert_type(bits, _f32)


def _scaled_compress(x32: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """fp32 (n,) → (fp16 values, per-block fp32 scales), n % BLOCK == 0."""
    blocks = x32.reshape(-1, _SCALE_BLOCK)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(amax > 0, amax / _F16_MAX, 1.0)
    vals = (blocks / scale[:, None]).astype(jnp.float16).reshape(-1)
    return vals, scale


def _scaled_expand(vals: jax.Array, scale: jax.Array) -> jax.Array:
    blocks = vals.reshape(-1, _SCALE_BLOCK).astype(_f32)
    return (blocks * scale[:, None]).reshape(-1)


class DistributedFusedAdam:
    """ZeRO-2 Adam over a mesh data axis.

    Usage::

        mesh = get_mesh("data")
        opt = DistributedFusedAdam(params, mesh, lr=1e-3)
        params = opt.step(grads)          # grads: one (already-summed or
                                          # per-host identical) pytree

    or with param groups::

        opt = DistributedFusedAdam(
            [{"params": decay_tree, "weight_decay": 0.01},
             {"params": nodecay_tree, "weight_decay": 0.0, "lr": 2e-3}],
            mesh)

    Under jit the step is: flatten grads → reduce-scatter (via sharding
    constraint) → [global-norm clip] → sharded fused Adam on the state shards
    → all-gather params. ``grad_sync_dtype`` lowers the reduce-scatter
    payload (bf16 grads ride a half-width collective).
    """

    def __init__(self, params: Any, mesh: Mesh, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-8, adam_w_mode: bool = True,
                 weight_decay: float = 0.0, axis: str = "data",
                 redundant_axis: Optional[str] = None,  # 2D grid: pass a 2D
                 # mesh (axis, redundant_axis); P(axis) shardings replicate
                 # the state across the redundant axis automatically — the
                 # reference's shard × replica process grid (:316-328)
                 state_dtype=jnp.float32, grad_sync_dtype=None,
                 store_param_remainders: bool = False,
                 with_scaled_states: bool = False,
                 max_grad_norm: float = 0.0,
                 overlap_grad_sync: bool = True,
                 overlap_param_sync: bool = True,
                 bucket_cap_mb: int = 100, pipeline_size: int = 2,
                 abstract_state: bool = False, **_compat):
        # overlap_*/bucket_cap/pipeline knobs: XLA's latency-hiding scheduler
        # owns these on TPU; accepted for API parity.
        self.mesh = mesh
        self.axis = axis
        self.redundant_axis = redundant_axis  # state replicated over it
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.state_dtype = state_dtype
        self.grad_sync_dtype = grad_sync_dtype
        self.store_param_remainders = store_param_remainders
        self.with_scaled_states = with_scaled_states
        self.max_grad_norm = max_grad_norm

        if with_scaled_states and store_param_remainders:
            raise ValueError("with_scaled_states and store_param_remainders "
                             "are mutually exclusive (as in the reference)")

        if redundant_axis is not None and \
                redundant_axis not in mesh.axis_names:
            raise ValueError(
                f"redundant_axis {redundant_axis!r} is not a mesh axis "
                f"{mesh.axis_names}; pass a 2D mesh (axis, redundant_axis) "
                "to get state replication over the redundant group")
        world = mesh.shape[axis]

        # ---- param groups: contiguous ranges of one flat buffer
        if (isinstance(params, (list, tuple)) and params
                and isinstance(params[0], dict) and "params" in params[0]):
            # torch's rule: a list of dicts each carrying a "params" key is
            # a param-group spec; any other pytree (incl. lists of plain
            # param dicts) is a single group
            for g in params:
                if not (isinstance(g, dict) and "params" in g):
                    raise ValueError(
                        "param groups must all be dicts with a 'params' "
                        "key (got a mix of group dicts and other entries)")
            groups = [dict(g) for g in params]
            self._single_group_input = False
        else:
            groups = [{"params": params}]
            self._single_group_input = True
        self.param_groups = []
        self._specs = []
        self._group_offsets = [0]
        flats = []
        for g in groups:
            spec = flat_spec(g["params"])
            self._specs.append(spec)
            flats.append(flatten(g["params"], spec, dtype=_f32))
            self._group_offsets.append(
                self._group_offsets[-1] + spec.total_size)
            self.param_groups.append({
                "lr": g.get("lr"),                      # None → step lr
                "weight_decay": g.get("weight_decay", weight_decay),
                "betas": g.get("betas", betas),
                "eps": g.get("eps", eps),
            })
        self._unpadded = self._group_offsets[-1]
        flat_p = jnp.concatenate(flats) if flats else jnp.zeros((0,), _f32)
        grid = 1024 * world
        n = -(-max(self._unpadded, 1) // grid) * grid
        if n != flat_p.size:
            flat_p = jnp.pad(flat_p, (0, n - flat_p.size))
        self._n = n

        shard = NamedSharding(mesh, P(axis))
        rep = NamedSharding(mesh, P())
        self._shard, self._rep = shard, rep

        put = _state_put(abstract_state)
        self.abstract_state = abstract_state
        if store_param_remainders:
            hi, lo = _split_f32(flat_p)
            self._master_hi = put(hi, shard)
            self._master_lo = put(lo, shard)
        else:
            self._master = put(flat_p, shard)
        if with_scaled_states:
            nblk = self._n // _SCALE_BLOCK
            self._m = put(jnp.zeros((self._n,), jnp.float16), shard)
            self._v = put(jnp.zeros((self._n,), jnp.float16), shard)
            self._m_scale = put(jnp.ones((nblk,), _f32), shard)
            self._v_scale = put(jnp.ones((nblk,), _f32), shard)
        else:
            self._m = put(jnp.zeros((self._n,), state_dtype), shard)
            self._v = put(jnp.zeros((self._n,), state_dtype), shard)
            self._m_scale = self._v_scale = None
        self._params = self._unflatten_groups(flat_p)
        self._step = jnp.zeros((), jnp.int32)
        self._acc = None  # lazy grad-accumulation buffer (sharded flat)
        self._jit_step = None
        self._jit_acc = None
        self._last_grad_norm = None

    # ---------------------------------------------------------------- helpers
    def _unflatten_groups(self, flat):
        trees = [unflatten(
            jax.lax.dynamic_slice_in_dim(flat, off, spec.total_size, axis=0),
            spec)
            for off, spec in zip(self._group_offsets, self._specs)]
        return trees[0] if self._single_group_input else trees

    def _validate_grads(self, grads):
        """Eager structural checks (zip would silently truncate)."""
        if self._single_group_input:
            grads = [grads]
        elif not isinstance(grads, (list, tuple)) or \
                len(grads) != len(self._specs):
            raise ValueError(
                f"param-group optimizer expects a list of "
                f"{len(self._specs)} per-group grad pytrees (one per "
                "constructor group)")
        for g, spec in zip(grads, self._specs):
            nl = len(jax.tree_util.tree_leaves(g))
            if nl != spec.num_leaves:
                raise ValueError(f"grad pytree has {nl} leaves, group "
                                 f"expects {spec.num_leaves}")

    def _flatten_grads(self, grads):
        """Pure-jnp pack (runs INSIDE the jitted step, fused with the
        reduce-scatter ingest — no eager per-leaf dispatch on the hot path)."""
        if self._single_group_input:
            grads = [grads]
        gdt = self.grad_sync_dtype or _f32
        parts = [flatten(g, spec, dtype=gdt)
                 for g, spec in zip(grads, self._specs)]
        flat = jnp.concatenate(parts) if parts else jnp.zeros((0,), gdt)
        if flat.size != self._n:
            flat = jnp.pad(flat, (0, self._n - flat.size))
        return flat

    def _group_vectors(self, step_lr):
        """(G,) per-group hyperparameter vectors for the jitted step."""
        gs = self.param_groups
        return (
            jnp.asarray([step_lr if g["lr"] is None else g["lr"]
                         for g in gs], _f32),
            jnp.asarray([g["weight_decay"] for g in gs], _f32),
            jnp.asarray([g["betas"][0] for g in gs], _f32),
            jnp.asarray([g["betas"][1] for g in gs], _f32),
            jnp.asarray([g["eps"] for g in gs], _f32),
        )

    # ------------------------------------------------------------------ step
    def _build_step(self):
        shard_s, rep_s = self._shard, self._rep
        adam_w, bias_corr = self.adam_w_mode, self.bias_correction
        remainders = self.store_param_remainders
        scaled = self.with_scaled_states
        max_gn = self.max_grad_norm
        n = self._n
        G = len(self.param_groups)
        bounds = tuple(self._group_offsets[1:])  # static group ends

        def per_element(vec):
            """Expand a (G,) group vector to (n,) by the static boundaries."""
            if G == 1:
                return vec[0]
            idx = jax.lax.iota(jnp.int32, n)
            gid = jnp.zeros((n,), jnp.int32)
            for end in bounds[:-1]:
                gid = gid + (idx >= end).astype(jnp.int32)
            return jnp.take(vec, gid)

        def step_fn(state, flat_g, step, inv_scale, found_inf,
                    lr_vec, wd_vec, b1_vec, b2_vec, eps_vec):
            # ZeRO reduce-scatter point: constrain the grad buffer to the
            # shard layout; XLA emits reduce-scatter when producers are
            # replicated/partial
            flat_g = jax.lax.with_sharding_constraint(flat_g, shard_s)
            g32 = flat_g.astype(_f32) * inv_scale

            grad_norm = jnp.sqrt(jnp.sum(g32 * g32))
            if max_gn > 0:
                # integrated clip (ref :2275): one fused scale on the shard
                clip = jnp.minimum(1.0, max_gn / (grad_norm + 1e-6))
                g32 = g32 * clip

            if remainders:
                p32 = _join_f32(state["hi"], state["lo"])
            else:
                p32 = state["p"].astype(_f32)
            if scaled:
                m32 = _scaled_expand(state["m"], state["m_scale"])
                v32 = _scaled_expand(state["v"], state["v_scale"])
            else:
                m32 = state["m"].astype(_f32)
                v32 = state["v"].astype(_f32)

            lr_e = per_element(lr_vec)
            wd_e = per_element(wd_vec)
            b1_e = per_element(b1_vec)
            b2_e = per_element(b2_vec)
            eps_e = per_element(eps_vec)

            if not adam_w:
                g32 = g32 + wd_e * p32
            m_new = b1_e * m32 + (1 - b1_e) * g32
            v_new = b2_e * v32 + (1 - b2_e) * g32 * g32
            stepf = step.astype(_f32)
            if bias_corr:
                # pow on the (G,) vectors, expanded after — not n pows
                bc1 = per_element(1 - jnp.power(b1_vec, stepf))
                bc2 = per_element(1 - jnp.power(b2_vec, stepf))
            else:
                bc1 = bc2 = _f32(1.0)
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps_e)
            if adam_w:
                upd = upd + wd_e * p32
            p_new = p32 - lr_e * upd

            keep = found_inf
            p_new = jnp.where(keep, p32, p_new)
            m_keep = jnp.where(keep, m32, m_new)
            v_keep = jnp.where(keep, v32, v_new)
            # state outputs stay in the shard layout (ZeRO memory win)
            p_new = jax.lax.with_sharding_constraint(p_new, shard_s)

            out = {}
            if scaled:
                mv, ms = _scaled_compress(m_keep)
                vv, vs = _scaled_compress(v_keep)
                out["m"] = jax.lax.with_sharding_constraint(mv, shard_s)
                out["v"] = jax.lax.with_sharding_constraint(vv, shard_s)
                out["m_scale"] = jax.lax.with_sharding_constraint(ms, shard_s)
                out["v_scale"] = jax.lax.with_sharding_constraint(vs, shard_s)
            else:
                out["m"] = jax.lax.with_sharding_constraint(
                    m_keep.astype(state["m"].dtype), shard_s)
                out["v"] = jax.lax.with_sharding_constraint(
                    v_keep.astype(state["v"].dtype), shard_s)

            # ZeRO all-gather point: params replicated for the next forward
            full = jax.lax.with_sharding_constraint(p_new, rep_s)

            if remainders:
                out["hi"], out["lo"] = _split_f32(p_new)
            else:
                out["p"] = p_new
            return out, full, grad_norm

        def step_tree(state, grads, *rest):
            return step_fn(state, self._flatten_grads(grads), *rest)

        return (jax.jit(step_tree, donate_argnums=(0,)),
                jax.jit(step_fn, donate_argnums=(0, 1)))

    def _state_pack(self):
        out = {"m": self._m, "v": self._v}
        if self.with_scaled_states:
            out["m_scale"] = self._m_scale
            out["v_scale"] = self._v_scale
        if self.store_param_remainders:
            out["hi"], out["lo"] = self._master_hi, self._master_lo
        else:
            out["p"] = self._master
        return out

    def _state_unpack(self, state):
        self._m, self._v = state["m"], state["v"]
        if self.with_scaled_states:
            self._m_scale = state["m_scale"]
            self._v_scale = state["v_scale"]
        if self.store_param_remainders:
            self._master_hi, self._master_lo = state["hi"], state["lo"]
        else:
            self._master = state["p"]

    def accumulate(self, grads: Any, inv_scale=1.0):
        """Add one micro-batch's grads into the sharded accumulation buffer
        (the reference's hook-accumulated main_grad flow). ``step()`` with no
        grads consumes it."""
        self._check_concrete("accumulate()")
        if self._jit_acc is None:
            def acc_fn(acc, grads, inv_scale):
                flat = self._flatten_grads(grads).astype(_f32) * inv_scale
                flat = jax.lax.with_sharding_constraint(flat, self._shard)
                return acc + flat

            self._jit_acc = jax.jit(acc_fn, donate_argnums=(0,))
        self._validate_grads(grads)
        if self._acc is None:
            self._acc = jax.device_put(jnp.zeros((self._n,), _f32),
                                       self._shard)
        with self.mesh:
            self._acc = self._jit_acc(self._acc, grads,
                                      jnp.asarray(inv_scale, _f32))

    def _check_concrete(self, what: str):
        if self.abstract_state:
            raise RuntimeError(
                f"{what} requires runtime state, but this instance was "
                "built with abstract_state=True (compile-only: state is "
                "shape structs for AOT lowering, tools/stack_aot.py)")

    def step(self, grads: Any = None, lr: Optional[float] = None,
             inv_scale=1.0, found_inf=False):
        self._check_concrete("step()")
        if self._jit_step is None:
            self._jit_step = self._build_step()
        jit_tree, jit_flat = self._jit_step
        consumed_acc = False
        if grads is None:
            if self._acc is None:
                raise ValueError("step() without grads requires prior "
                                 "accumulate() calls")
            try:
                scale_is_noop = float(inv_scale) == 1.0
            except TypeError:  # traced value: can't verify, refuse
                scale_is_noop = False
            if not scale_is_noop:
                raise ValueError(
                    "inv_scale must be applied per-microbatch via "
                    "accumulate(grads, inv_scale=...); step() cannot "
                    "rescale the already-accumulated buffer")
            gin, run = self._acc, jit_flat
            consumed_acc = True
        else:
            self._validate_grads(grads)
            gin, run = grads, jit_tree
        # compute the stepped counter but assign it (and drop the
        # accumulation buffer) only after the jitted step succeeds: a
        # raising step() must not lose grads or skew bias correction
        next_step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        vecs = self._group_vectors(self.lr if lr is None else lr)
        with self.mesh:
            state, full, gnorm = run(
                self._state_pack(), gin, next_step,
                jnp.asarray(inv_scale, _f32),
                jnp.asarray(found_inf, jnp.bool_), *vecs)
        self._step = next_step
        if consumed_acc:
            self._acc = None  # buffer was donated into the jitted step
        self._state_unpack(state)
        self._last_grad_norm = gnorm
        self._params = self._unflatten_groups(full)
        return self._params

    # ------------------------------------------------------------- utilities
    @property
    def parameters(self):
        return self._params

    @property
    def grad_norm_last_step(self):
        """Global grad norm computed inside the last ``step`` (pre-clip)."""
        return self._last_grad_norm

    def set_parameters(self, params: Any):
        """Overwrite params AND the sharded fp32 master (e.g. after ASP
        masking) so the source-of-truth flat buffer stays consistent."""
        self._params = params
        if self._single_group_input:
            params = [params]
        parts = [flatten(p, spec, dtype=_f32)
                 for p, spec in zip(params, self._specs)]
        flat = jnp.concatenate(parts)
        if flat.size != self._n:
            flat = jnp.pad(flat, (0, self._n - flat.size))
        if self.store_param_remainders:
            hi, lo = _split_f32(flat)
            self._master_hi = jax.device_put(hi, self._shard)
            self._master_lo = jax.device_put(lo, self._shard)
        else:
            self._master = jax.device_put(flat, self._shard)

    def grad_norm(self, grads) -> jax.Array:
        """Global L2 grad norm (ref ``_local_grad_norm`` + all-reduce :2150)."""
        g, _ = multi_tensor_l2norm(grads)
        return g

    def clip_grad_norm(self, grads, max_norm: float):
        """Standalone clip (ref :2275): returns (clipped grads, norm).
        Prefer ``max_grad_norm`` in the constructor — that fuses the clip
        into the sharded step."""
        norm = self.grad_norm(grads)
        coef = jnp.minimum(1.0, max_norm / (norm + 1e-6))
        return jax.tree_util.tree_map(lambda g: g * coef, grads), norm

    def zero_grad(self, set_to_none: bool = True):
        self._acc = None

    # ---------------------------------------------------------- checkpointing
    def _master_f32(self):
        return (_join_f32(self._master_hi, self._master_lo)
                if self.store_param_remainders else self._master)

    def _state_f32(self):
        if self.with_scaled_states:
            return (_scaled_expand(self._m, self._m_scale),
                    _scaled_expand(self._v, self._v_scale))
        return self._m, self._v

    def state_dict(self, gather_on_root: bool = True):
        """v1 semantics (ref :2907): gather shards → full host arrays."""
        self._check_concrete("state_dict()")
        m, v = self._state_f32()
        return {
            "step": int(self._step),
            "lr": self.lr,
            "master": np.asarray(self._master_f32()),
            "m": np.asarray(m),
            "v": np.asarray(v),
        }

    def sharded_state_dict(self):
        """v2 semantics (ref :3059-3329): per-shard state, no gather. Each
        entry maps shard index → host array; ``unpadded`` records the true
        payload so a different world size can re-pad on load."""
        self._check_concrete("sharded_state_dict()")
        world = self.mesh.shape[self.axis]
        shard_size = self._n // world

        def shards(x):
            # key by shard POSITION and dedup: on a 2D (shard × replica)
            # grid each shard index appears once per replica
            out = {}
            for s in x.addressable_shards:
                idx = (s.index[0].start or 0) // shard_size
                if idx not in out:
                    out[idx] = np.asarray(s.data)
            return out

        m, v = self._state_f32()
        return {
            "step": int(self._step),
            "world": world,
            "total_size": self._n,
            "unpadded": self._unpadded,
            "master": shards(self._master_f32()),
            "m": shards(m),
            "v": shards(v),
        }

    def load_state_dict(self, sd):
        self._check_concrete("load_state_dict()")
        self._step = jnp.asarray(sd["step"], jnp.int32)
        self.lr = sd.get("lr", self.lr)
        if "world" in sd:  # sharded (v2) checkpoint: concatenate shards
            if "unpadded" in sd and sd["unpadded"] != self._unpadded:
                raise ValueError(
                    f"checkpoint payload is {sd['unpadded']} elements but "
                    f"this optimizer's param layout is {self._unpadded} — "
                    "the model/group structure differs from the one saved")

            def join(d):
                return np.concatenate([d[i] for i in sorted(d)])

            master = join(sd["master"])
            m = join(sd["m"])
            v = join(sd["v"])
        else:
            master = np.asarray(sd["master"])
            m = np.asarray(sd["m"])
            v = np.asarray(sd["v"])
            if master.shape[0] < self._unpadded:
                raise ValueError(
                    f"checkpoint master has {master.shape[0]} elements, "
                    f"fewer than this optimizer's payload {self._unpadded}")

        def fit(x):
            # world-size resharding: the unpadded payload layout is
            # world-independent; only the zero tail padding differs
            if x.shape[0] > self._n:
                x = x[:self._n]
            elif x.shape[0] < self._n:
                x = np.pad(x, (0, self._n - x.shape[0]))
            return jnp.asarray(x)

        master, m, v = fit(master), fit(m), fit(v)
        if self.store_param_remainders:
            hi, lo = _split_f32(master)
            self._master_hi = jax.device_put(hi, self._shard)
            self._master_lo = jax.device_put(lo, self._shard)
        else:
            self._master = jax.device_put(master, self._shard)
        if self.with_scaled_states:
            mv, ms = _scaled_compress(m)
            vv, vs = _scaled_compress(v)
            self._m = jax.device_put(mv, self._shard)
            self._v = jax.device_put(vv, self._shard)
            self._m_scale = jax.device_put(ms, self._shard)
            self._v_scale = jax.device_put(vs, self._shard)
        else:
            self._m = jax.device_put(m.astype(self.state_dtype), self._shard)
            self._v = jax.device_put(v.astype(self.state_dtype), self._shard)
        self._params = self._unflatten_groups(master)
        self._acc = None  # pending pre-restore microbatches must not leak
        self._jit_step = None
