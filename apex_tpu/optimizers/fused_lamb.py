"""FusedLAMB — TPU equivalent of ``apex/optimizers/fused_lamb.py`` (:114 step).

Two-phase semantics of the reference preserved: fused global grad-norm
(multi_tensor_l2norm, fused_lamb.py:145-158) feeding a clip, then the LAMB
update with per-tensor trust ratios (csrc/multi_tensor_lamb.cu stage1/stage2).
"""

from __future__ import annotations

from typing import Any

from apex_tpu.optimizers._base import FusedOptimizerBase, zeros_like_f32
from apex_tpu.optimizers.functional import lamb_update


class FusedLAMB(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 amsgrad: bool = False, adam_w_mode: bool = True,
                 grad_averaging: bool = True, max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        super().__init__(params, lr)
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.state = {"m": zeros_like_f32(params), "v": zeros_like_f32(params)}
        self.last_grad_norm = None

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        p, m, v, gnorm = lamb_update(
            params, grads, state["m"], state["v"], step=step, lr=lr,
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay,
            bias_correction=self.bias_correction,
            grad_averaging=self.grad_averaging,
            max_grad_norm=self.max_grad_norm, use_nvlamb=self.use_nvlamb,
            adam_w_mode=self.adam_w_mode, inv_scale=inv_scale,
            found_inf=found_inf)
        return p, {"m": m, "v": v}
