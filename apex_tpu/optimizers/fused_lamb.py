"""FusedLAMB — TPU equivalent of ``apex/optimizers/fused_lamb.py`` (:114 step).

Two-phase semantics of the reference preserved: fused global grad-norm
(multi_tensor_l2norm, fused_lamb.py:145-158) feeding a clip, then the LAMB
update with per-tensor trust ratios (csrc/multi_tensor_lamb.cu stage1/stage2).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import FusedOptimizerBase, zeros_like_f32
from apex_tpu.optimizers.functional import lamb_update
from apex_tpu.ops.pallas.fused_opt_kernels import (fused_lamb_flat,
                                                   row_segment_ids)
from apex_tpu.utils.flatten import flat_spec, flatten, unflatten


class FusedLAMB(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.9, 0.999),
                 eps: float = 1e-6, weight_decay: float = 0.01,
                 amsgrad: bool = False, adam_w_mode: bool = True,
                 grad_averaging: bool = True, max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False, use_flat: bool = True):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        super().__init__(params, lr)
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.use_flat = use_flat
        if use_flat:
            # flat Pallas path (multi_tensor_lamb.cu stage1/stage2 analog)
            self._spec = flat_spec(params)
            self._flat_p = flatten(params, self._spec, dtype=jnp.float32,
                                   pad_to=1024)
            self._row_ids = row_segment_ids(self._spec, self._flat_p.size)
            self.state = {
                "m": jnp.zeros_like(self._flat_p),
                "v": jnp.zeros_like(self._flat_p),
            }
        else:
            self.state = {"m": zeros_like_f32(params),
                          "v": zeros_like_f32(params)}
        self.last_grad_norm = None

    def step(self, grads: Any, lr: Optional[float] = None,
             inv_scale=1.0, found_inf=False):
        if not self.use_flat:
            return super().step(grads, lr=lr, inv_scale=inv_scale,
                                found_inf=found_inf)
        self._step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        flat_g = flatten(grads, self._spec, dtype=jnp.float32,
                         pad_to=self._flat_p.size)
        p, m, v, gnorm = fused_lamb_flat(
            self._flat_p, flat_g, self.state["m"], self.state["v"],
            self._row_ids, num_tensors=self._spec.num_leaves,
            lr=jnp.asarray(self._lr if lr is None else lr, jnp.float32),
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay, step=self._step,
            bias_correction=self.bias_correction,
            grad_averaging=self.grad_averaging,
            max_grad_norm=self.max_grad_norm, use_nvlamb=self.use_nvlamb,
            adam_w_mode=self.adam_w_mode, inv_scale=inv_scale,
            found_inf=found_inf)
        self._flat_p, self.state["m"], self.state["v"] = p, m, v
        self.last_grad_norm = gnorm
        self._params = unflatten(p, self._spec)
        return self._params

    def set_parameters(self, params):
        super().set_parameters(params)
        if self.use_flat:
            self._flat_p = flatten(params, self._spec, dtype=jnp.float32,
                                   pad_to=1024)

    def load_state_dict(self, sd):
        super().load_state_dict(sd)
        if self.use_flat:
            self._flat_p = flatten(self._params, self._spec,
                                   dtype=jnp.float32, pad_to=1024)
            if not isinstance(self.state["m"], jax.Array):
                # tree-path checkpoint: repack into the flat layout
                self.state = {
                    "m": flatten(self.state["m"], self._spec,
                                 dtype=jnp.float32, pad_to=1024),
                    "v": flatten(self.state["v"], self._spec,
                                 dtype=jnp.float32, pad_to=1024),
                }

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        p, m, v, gnorm = lamb_update(
            params, grads, state["m"], state["v"], step=step, lr=lr,
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay,
            bias_correction=self.bias_correction,
            grad_averaging=self.grad_averaging,
            max_grad_norm=self.max_grad_norm, use_nvlamb=self.use_nvlamb,
            adam_w_mode=self.adam_w_mode, inv_scale=inv_scale,
            found_inf=found_inf)
        return p, {"m": m, "v": v}
