"""Optimizer family — parity with ``apex/optimizers/__init__.py:1-6`` plus the
contrib distributed (ZeRO) optimizers."""

from apex_tpu.optimizers.fused_adam import FusedAdam, FusedAdamW  # noqa: F401
from apex_tpu.optimizers.fused_sgd import FusedSGD  # noqa: F401
from apex_tpu.optimizers.fused_lamb import FusedLAMB  # noqa: F401
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import FusedAdagrad  # noqa: F401
from apex_tpu.optimizers.fused_mixed_precision_lamb import (  # noqa: F401
    FusedMixedPrecisionLamb,
)
from apex_tpu.optimizers import functional  # noqa: F401
