"""FusedSGD — TPU equivalent of ``apex/optimizers/fused_sgd.py``.

SGD with momentum, dampening, nesterov; ``wd_after_momentum`` and
``materialize_master_grads`` flags mirror the amp-O2-style master-weight
training knobs of the reference (csrc/multi_tensor_sgd_kernel.cu depths 2-4).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from apex_tpu.optimizers._base import (FusedOptimizerBase, master_copy,
                                       zeros_like_f32)
from apex_tpu.optimizers.functional import sgd_update


class FusedSGD(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float, momentum: float = 0.0,
                 dampening: float = 0.0, weight_decay: float = 0.0,
                 nesterov: bool = False, wd_after_momentum: bool = False,
                 materialize_master_grads: bool = True,
                 master_weights: bool = False):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        super().__init__(params, lr)
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.materialize_master_grads = materialize_master_grads
        self.master_weights = master_weights
        self.state = {"momentum_buffer": zeros_like_f32(params)}
        if master_weights:
            self.state["master"] = master_copy(params)

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        out = sgd_update(
            params, grads, state["momentum_buffer"], lr=lr,
            momentum=self.momentum, dampening=self.dampening,
            weight_decay=self.weight_decay, nesterov=self.nesterov,
            wd_after_momentum=self.wd_after_momentum,
            first_step=(step == 1), inv_scale=inv_scale,
            found_inf=found_inf, master=state.get("master"))
        if self.master_weights:
            p, buf, mst = out
            return p, {"momentum_buffer": buf, "master": mst}
        p, buf = out
        return p, {"momentum_buffer": buf}
