"""FusedSGD — TPU equivalent of ``apex/optimizers/fused_sgd.py``.

SGD with momentum, dampening, nesterov; ``wd_after_momentum`` and
``materialize_master_grads`` flags mirror the amp-O2-style master-weight
training knobs of the reference (csrc/multi_tensor_sgd_kernel.cu depths 2-4).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from apex_tpu.optimizers._base import (FusedOptimizerBase, master_copy,
                                       zeros_like_f32)
from apex_tpu.optimizers.functional import sgd_update
from apex_tpu.utils.flatten import flat_spec, flatten, unflatten


class FusedSGD(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float, momentum: float = 0.0,
                 dampening: float = 0.0, weight_decay: float = 0.0,
                 nesterov: bool = False, wd_after_momentum: bool = False,
                 materialize_master_grads: bool = True,
                 master_weights: bool = False, use_flat: bool = False):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        super().__init__(params, lr)
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.materialize_master_grads = materialize_master_grads
        self.master_weights = master_weights
        self.use_flat = use_flat
        if use_flat:
            self._spec = flat_spec(params)
            # master_weights: the flat buffer IS the fp32 master; params are
            # its low-precision unflatten views
            self._flat_p = flatten(
                params, self._spec,
                dtype=jnp.float32 if master_weights else None, pad_to=1024)
            self.state = {"momentum_buffer": jnp.zeros_like(
                self._flat_p, dtype=jnp.float32)}
        else:
            self.state = {"momentum_buffer": zeros_like_f32(params)}
            if master_weights:
                self.state["master"] = master_copy(params)

    def step(self, grads: Any, lr=None, inv_scale=1.0, found_inf=False):
        if not self.use_flat:
            return super().step(grads, lr=lr, inv_scale=inv_scale,
                                found_inf=found_inf)
        from apex_tpu.ops.pallas.fused_sgd_kernel import fused_sgd_flat
        first = self._step == 0
        self._step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        flat_g = flatten(grads, self._spec, dtype=self._flat_p.dtype,
                         pad_to=self._flat_p.size)
        p, buf = fused_sgd_flat(
            self._flat_p, flat_g, self.state["momentum_buffer"],
            lr=jnp.asarray(self._lr if lr is None else lr, jnp.float32),
            momentum=self.momentum, dampening=self.dampening,
            weight_decay=self.weight_decay, nesterov=self.nesterov,
            wd_after_momentum=self.wd_after_momentum, inv_scale=inv_scale,
            found_inf=found_inf, first_step=first)
        self._flat_p, self.state["momentum_buffer"] = p, buf
        self._params = unflatten(p, self._spec)
        return self._params

    def set_parameters(self, params):
        super().set_parameters(params)
        if self.use_flat:
            self._flat_p = flatten(params, self._spec,
                                   dtype=self._flat_p.dtype, pad_to=1024)

    def state_dict(self):
        sd = super().state_dict()
        if self.use_flat and self.master_weights:
            # the flat fp32 master is NOT derivable from low-precision params
            import numpy as np
            sd["flat_p"] = np.asarray(self._flat_p)
        return sd

    def load_state_dict(self, sd):
        super().load_state_dict(sd)
        if self.use_flat:
            if "flat_p" in sd:
                self._flat_p = jnp.asarray(sd["flat_p"])
            else:
                self._flat_p = flatten(self._params, self._spec,
                                       dtype=self._flat_p.dtype, pad_to=1024)

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        out = sgd_update(
            params, grads, state["momentum_buffer"], lr=lr,
            momentum=self.momentum, dampening=self.dampening,
            weight_decay=self.weight_decay, nesterov=self.nesterov,
            wd_after_momentum=self.wd_after_momentum,
            first_step=(step == 1), inv_scale=inv_scale,
            found_inf=found_inf, master=state.get("master"))
        if self.master_weights:
            p, buf, mst = out
            return p, {"momentum_buffer": buf, "master": mst}
        p, buf = out
        return p, {"momentum_buffer": buf}
