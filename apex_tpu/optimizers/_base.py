"""Shared machinery for the stateful apex-style optimizer frontends.

The reference optimizers are ``torch.optim.Optimizer`` subclasses holding
mutable state and exposing ``.step()`` (e.g. apex/optimizers/fused_adam.py:146).
The TPU equivalents keep ALL state (params, moments, step counter) as device
arrays inside one jitted, donated update — so ``.step(grads)`` is a single
compiled program with no host sync ("capturable" by construction,
fused_adam.py:234-308).

Two usage styles:
- stateful: ``opt = FusedAdam(params); params = opt.step(grads)``
- functional: each optimizer also exposes its pure update in
  :mod:`apex_tpu.optimizers.functional` for use inside user jit/pjit loops.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


class FusedOptimizerBase:
    """Base for stateful frontends. Subclasses set ``self._update_fn`` (pure:
    (params, grads, state, step, lr, inv_scale, found_inf) -> (params, state))
    and build initial ``self.state`` (a pytree dict)."""

    def __init__(self, params: Any, lr: float):
        # own a copy: step() donates the param buffers into the jitted update,
        # which must not invalidate arrays the caller still holds
        self._params = jax.tree_util.tree_map(
            lambda p: jnp.array(p, copy=True), params)
        self._lr = lr
        self._step = jnp.zeros((), jnp.int32)
        self.state: Dict[str, Any] = {}
        self._jitted: Optional[Callable] = None

    # -- core ---------------------------------------------------------------
    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        raise NotImplementedError

    def _stepped_update(self, params, grads, state, prev_step, lr, inv_scale,
                        found_inf):
        # the step counter only advances on applied (non-overflow) steps,
        # matching the reference capturable semantics (fused_adam.py:181:
        # step incremented only when the overflow buffer is clear)
        found_inf = jnp.asarray(found_inf, jnp.bool_)
        step = prev_step + jnp.where(found_inf, 0, 1).astype(jnp.int32)
        params, state = self._update(params, grads, state, step, lr,
                                     inv_scale, found_inf)
        return params, state, step

    def _get_jitted(self):
        if self._jitted is None:
            # donate only optimizer state: params are returned to the caller,
            # who may hold them across steps (state is internal)
            self._jitted = jax.jit(self._stepped_update, donate_argnums=(2,))
        return self._jitted

    def step(self, grads: Any, lr: Optional[float] = None,
             inv_scale=1.0, found_inf=False):
        """Apply one optimizer step; returns (and stores) updated params."""
        lr_val = jnp.asarray(self._lr if lr is None else lr, jnp.float32)
        params, state, step = self._get_jitted()(
            self._params, grads, self.state, self._step, lr_val,
            jnp.asarray(inv_scale, jnp.float32),
            jnp.asarray(found_inf, jnp.bool_))
        self._params, self.state, self._step = params, state, step
        return params

    # -- torch-optim-compatible surface ------------------------------------
    @property
    def parameters(self):
        return self._params

    @property
    def param_groups(self):
        # single-group view for API compatibility
        return [{"params": jax.tree_util.tree_leaves(self._params),
                 "lr": self._lr}]

    def zero_grad(self, set_to_none: bool = True):
        """No-op: grads are function outputs in JAX (kept for API parity)."""

    def set_parameters(self, params: Any):
        """Overwrite the optimizer's view of the params (e.g. after external
        pruning/masking). Subclasses with internal flat buffers override to
        keep those in sync."""
        self._params = params

    def state_dict(self) -> Dict[str, Any]:
        """Checkpointable state (host numpy), ≈ torch ``state_dict``."""
        return {
            "step": int(self._step),
            "lr": self._lr,
            "state": jax.tree_util.tree_map(np.asarray, self.state),
            "params": jax.tree_util.tree_map(np.asarray, self._params),
        }

    def load_state_dict(self, sd: Dict[str, Any]):
        self._step = jnp.asarray(sd["step"], jnp.int32)
        self._lr = sd["lr"]
        self.state = jax.tree_util.tree_map(jnp.asarray, sd["state"])
        self._params = jax.tree_util.tree_map(jnp.asarray, sd["params"])
        self._jitted = None


def zeros_like_f32(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), tree)


def scalar_zeros(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros((), jnp.float32), tree)


def master_copy(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32), tree)
