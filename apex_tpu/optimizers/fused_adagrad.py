"""FusedAdagrad — TPU equivalent of ``apex/optimizers/fused_adagrad.py`` (:75 step).

``adagrad_w_mode`` gives decoupled weight decay (csrc/multi_tensor_adagrad.cu).
"""

from __future__ import annotations

from typing import Any

from apex_tpu.optimizers._base import FusedOptimizerBase, zeros_like_f32
from apex_tpu.optimizers.functional import adagrad_update


class FusedAdagrad(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, adagrad_w_mode: bool = False,
                 set_grad_none: bool = True):
        super().__init__(params, lr)
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode
        self.state = {"sum": zeros_like_f32(params)}

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        p, h = adagrad_update(
            params, grads, state["sum"], lr=lr, eps=self.eps,
            weight_decay=self.weight_decay,
            adagrad_w_mode=self.adagrad_w_mode, inv_scale=inv_scale,
            found_inf=found_inf)
        return p, {"sum": h}
