"""FusedAdagrad — TPU equivalent of ``apex/optimizers/fused_adagrad.py`` (:75 step).

``adagrad_w_mode`` gives decoupled weight decay (csrc/multi_tensor_adagrad.cu).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import FusedOptimizerBase, zeros_like_f32
from apex_tpu.optimizers.functional import adagrad_update
from apex_tpu.ops.pallas.fused_opt_kernels import fused_adagrad_flat
from apex_tpu.utils.flatten import flat_spec, flatten, unflatten


class FusedAdagrad(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, adagrad_w_mode: bool = False,
                 set_grad_none: bool = True, use_flat: bool = True):
        super().__init__(params, lr)
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode
        self.use_flat = use_flat
        if use_flat:
            self._spec = flat_spec(params)
            self._flat_p = flatten(params, self._spec, dtype=jnp.float32,
                                   pad_to=1024)
            self.state = {"sum": jnp.zeros_like(self._flat_p)}
        else:
            self.state = {"sum": zeros_like_f32(params)}

    def step(self, grads: Any, lr: Optional[float] = None,
             inv_scale=1.0, found_inf=False):
        if not self.use_flat:
            return super().step(grads, lr=lr, inv_scale=inv_scale,
                                found_inf=found_inf)
        self._step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        flat_g = flatten(grads, self._spec, dtype=jnp.float32,
                         pad_to=self._flat_p.size)
        p, h = fused_adagrad_flat(
            self._flat_p, flat_g, self.state["sum"],
            lr=jnp.asarray(self._lr if lr is None else lr, jnp.float32),
            eps=self.eps, weight_decay=self.weight_decay,
            adagrad_w_mode=self.adagrad_w_mode, inv_scale=inv_scale,
            found_inf=found_inf)
        self._flat_p, self.state["sum"] = p, h
        self._params = unflatten(p, self._spec)
        return self._params

    def set_parameters(self, params):
        super().set_parameters(params)
        if self.use_flat:
            self._flat_p = flatten(params, self._spec, dtype=jnp.float32,
                                   pad_to=1024)

    def load_state_dict(self, sd):
        super().load_state_dict(sd)
        if self.use_flat:
            self._flat_p = flatten(self._params, self._spec,
                                   dtype=jnp.float32, pad_to=1024)
            if not isinstance(self.state["sum"], jax.Array):
                self.state = {"sum": flatten(self.state["sum"], self._spec,
                                             dtype=jnp.float32,
                                             pad_to=1024)}

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        p, h = adagrad_update(
            params, grads, state["sum"], lr=lr, eps=self.eps,
            weight_decay=self.weight_decay,
            adagrad_w_mode=self.adagrad_w_mode, inv_scale=inv_scale,
            found_inf=found_inf)
        return p, {"sum": h}
