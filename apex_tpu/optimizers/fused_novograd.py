"""FusedNovoGrad — TPU equivalent of ``apex/optimizers/fused_novograd.py`` (:126 step).

Per-tensor second-moment norm (``exp_avg_sq`` is one scalar per parameter
tensor), ``norm_type`` 0 (inf) / 2 (L2), ``init_zero`` initialization —
mirroring csrc/multi_tensor_novograd.cu ``NovoGradFunctor``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import (FusedOptimizerBase, scalar_zeros,
                                       zeros_like_f32)
from apex_tpu.optimizers.functional import novograd_update
from apex_tpu.ops.pallas.fused_opt_kernels import (fused_novograd_flat,
                                                   row_segment_ids)
from apex_tpu.utils.flatten import flat_spec, flatten, unflatten


class FusedNovoGrad(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.95, 0.98),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 amsgrad: bool = False, reg_inside_moment: bool = False,
                 grad_averaging: bool = True, norm_type: int = 2,
                 init_zero: bool = False, set_grad_none: bool = True,
                 use_flat: Optional[bool] = None):
        if amsgrad:
            raise RuntimeError(
                "FusedNovoGrad does not support the AMSGrad variant.")
        super().__init__(params, lr)
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.grad_averaging = grad_averaging
        self.norm_type = norm_type
        self.init_zero = init_zero
        # flat Pallas path needs the L2 norm_type (inf-norm → tree path)
        self.use_flat = (norm_type == 2) if use_flat is None else use_flat
        if self.use_flat and norm_type != 2:
            raise ValueError("use_flat requires norm_type=2")
        if self.use_flat:
            self._spec = flat_spec(params)
            self._flat_p = flatten(params, self._spec, dtype=jnp.float32,
                                   pad_to=1024)
            self._row_ids = row_segment_ids(self._spec, self._flat_p.size)
            self.state = {
                "m": jnp.zeros_like(self._flat_p),
                "v": jnp.zeros((self._spec.num_leaves,), jnp.float32),
            }
        else:
            self.state = {"m": zeros_like_f32(params),
                          "v": scalar_zeros(params)}

    def step(self, grads: Any, lr: Optional[float] = None,
             inv_scale=1.0, found_inf=False):
        if not self.use_flat:
            return super().step(grads, lr=lr, inv_scale=inv_scale,
                                found_inf=found_inf)
        self._step = self._step + jnp.where(
            jnp.asarray(found_inf, jnp.bool_), 0, 1).astype(jnp.int32)
        flat_g = flatten(grads, self._spec, dtype=jnp.float32,
                         pad_to=self._flat_p.size)
        p, m, v = fused_novograd_flat(
            self._flat_p, flat_g, self.state["m"], self.state["v"],
            self._row_ids, num_tensors=self._spec.num_leaves,
            lr=jnp.asarray(self._lr if lr is None else lr, jnp.float32),
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay, step=self._step,
            grad_averaging=self.grad_averaging,
            bias_correction=self.bias_correction, norm_type=self.norm_type,
            init_zero=self.init_zero, inv_scale=inv_scale,
            found_inf=found_inf)
        self._flat_p, self.state["m"], self.state["v"] = p, m, v
        self._params = unflatten(p, self._spec)
        return self._params

    def set_parameters(self, params):
        super().set_parameters(params)
        if self.use_flat:
            self._flat_p = flatten(params, self._spec, dtype=jnp.float32,
                                   pad_to=1024)

    def load_state_dict(self, sd):
        # parity note: the reference re-materializes per-group norm tensors on
        # load (fused_novograd.py:118); here v restores directly.
        super().load_state_dict(sd)
        if self.use_flat:
            self._flat_p = flatten(self._params, self._spec,
                                   dtype=jnp.float32, pad_to=1024)
            if not isinstance(self.state["m"], jax.Array):
                # tree-path checkpoint: m flat; v scalar-tree → (T,) vector
                self.state = {
                    "m": flatten(self.state["m"], self._spec,
                                 dtype=jnp.float32, pad_to=1024),
                    "v": jnp.stack([jnp.asarray(x, jnp.float32) for x in
                                    jax.tree_util.tree_leaves(
                                        self.state["v"])]),
                }

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        p, m, v = novograd_update(
            params, grads, state["m"], state["v"], step=step, lr=lr,
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay,
            grad_averaging=self.grad_averaging,
            bias_correction=self.bias_correction, norm_type=self.norm_type,
            init_zero=self.init_zero, inv_scale=inv_scale,
            found_inf=found_inf)
        return p, {"m": m, "v": v}
