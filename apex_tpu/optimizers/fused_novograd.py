"""FusedNovoGrad — TPU equivalent of ``apex/optimizers/fused_novograd.py`` (:126 step).

Per-tensor second-moment norm (``exp_avg_sq`` is one scalar per parameter
tensor), ``norm_type`` 0 (inf) / 2 (L2), ``init_zero`` initialization —
mirroring csrc/multi_tensor_novograd.cu ``NovoGradFunctor``.
"""

from __future__ import annotations

from typing import Any

from apex_tpu.optimizers._base import (FusedOptimizerBase, scalar_zeros,
                                       zeros_like_f32)
from apex_tpu.optimizers.functional import novograd_update


class FusedNovoGrad(FusedOptimizerBase):
    def __init__(self, params: Any, lr: float = 1e-3,
                 bias_correction: bool = True, betas=(0.95, 0.98),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 amsgrad: bool = False, reg_inside_moment: bool = False,
                 grad_averaging: bool = True, norm_type: int = 2,
                 init_zero: bool = False, set_grad_none: bool = True):
        if amsgrad:
            raise RuntimeError(
                "FusedNovoGrad does not support the AMSGrad variant.")
        super().__init__(params, lr)
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.grad_averaging = grad_averaging
        self.norm_type = norm_type
        self.init_zero = init_zero
        self.state = {"m": zeros_like_f32(params),
                      "v": scalar_zeros(params)}

    def _update(self, params, grads, state, step, lr, inv_scale, found_inf):
        p, m, v = novograd_update(
            params, grads, state["m"], state["v"], step=step, lr=lr,
            beta1=self.betas[0], beta2=self.betas[1], eps=self.eps,
            weight_decay=self.weight_decay,
            grad_averaging=self.grad_averaging,
            bias_correction=self.bias_correction, norm_type=self.norm_type,
            init_zero=self.init_zero, inv_scale=inv_scale,
            found_inf=found_inf)
        return p, {"m": m, "v": v}

    def load_state_dict(self, sd):
        # parity note: the reference re-materializes per-group norm tensors on
        # load (fused_novograd.py:118); here v is already a per-tensor scalar
        # tree restored directly.
        super().load_state_dict(sd)
