"""``apex-tpu-lint`` console-script shim.

The linter itself lives in ``tools/apexlint`` (it is a repo-development
tool — it ships with the source tree, not inside the library package, so
the library never imports its own linter). This shim locates the repo
root relative to the installed/source-tree ``apex_tpu`` package and
dispatches to :func:`tools.apexlint.cli.main`; a pip-installed wheel
without the source tree gets a clear error instead of a stack trace.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional


def _repo_root() -> Optional[str]:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    probe = os.path.join(root, "tools", "apexlint", "cli.py")
    return root if os.path.exists(probe) else None


def main(argv: Optional[List[str]] = None) -> int:
    root = _repo_root()
    if root is None:
        print("apex-tpu-lint: tools/apexlint not found next to the "
              "apex_tpu package — the linter runs from a source checkout "
              "(python -m tools.apexlint from the repo root)",
              file=sys.stderr)
        return 2
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.apexlint.cli import main as lint_main

    return lint_main(argv)


if __name__ == "__main__":
    sys.exit(main())
