"""On-chip compiled-kernel correctness artifact (VERDICT r2 item 3).

The test suite runs every Pallas kernel in interpret mode on a CPU mesh —
correct for semantics, blind to Mosaic compilation bugs (layout selection,
tiling, SMEM scalar plumbing). This script runs the COMPILED kernels on the
real chip and checks bit-level-independent parity against plain-jnp
references (the XLA-compiled math, a fully independent lowering path), the
TPU analog of the reference's on-device L0 tier
(/root/reference/tests/L0/run_test.py:21-30).

Coverage: the five flat optimizer kernels (adam [+master, +L2 mode], sgd,
lamb, novograd, adagrad), LayerNorm/RMSNorm fwd+bwd (incl. the
memory-efficient recompute-from-output backward), GroupNorm NHWC (+SiLU),
the Pallas row-tile softmax fwd+bwd (causal + masked), and flash attention
fwd+bwd (causal, arbitrary mask, ragged lengths, dropout determinism).

Output: CHIPCHECK.json — per-kernel {pass, max_err} + an overall ``ok``;
exit 0 iff every check passed ON the TPU backend. Driven like bench.py
(patient relay probe); a run that cannot reach the chip records
``backend != "tpu"`` and exits 2 — interpret-mode parity is the test
suite's job, not this artifact's.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _acquire_backend():
    from bench import wait_for_backend

    if os.environ.get("APEX_TPU_CHIPCHECK_SMOKE") == "1":
        # local smoke of the script logic (kernels in interpret mode).
        # The dev image's sitecustomize pins the platform to the TPU tunnel
        # and ignores JAX_PLATFORMS — switch through jax.config BEFORE any
        # backend init (same trick as tests/conftest.py).
        import jax

        jax.config.update("jax_platforms", "cpu")
        return jax, jax.default_backend()
    if not wait_for_backend(tag="chipcheck"):
        # NEVER import jax here: on a wedged relay the in-process backend
        # init hangs uninterruptibly in C. Record the failure and bail.
        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "CHIPCHECK.json"), "w") as f:
            json.dump({"backend": "unreachable", "ok": False}, f, indent=1)
        print(json.dumps({"ok": False, "backend": "unreachable"}))
        sys.exit(2)
    import jax

    return jax, jax.default_backend()


SMALL = False  # set in main() when running off-chip smoke


def _cmp(got, want, tol):
    import jax.numpy as jnp
    import numpy as np

    g = np.asarray(got.astype(jnp.float32) if hasattr(got, "astype") else got,
                   np.float32)
    w = np.asarray(want.astype(jnp.float32)
                   if hasattr(want, "astype") else want, np.float32)
    err = float(np.max(np.abs(g - w))) if g.size else 0.0
    scale = float(np.max(np.abs(w))) + 1e-12
    return err, err <= tol * max(1.0, scale)


def _tree_cmp(got_tree, want_tree, tol):
    import jax

    errs, oks = [], []
    for g, w in zip(jax.tree_util.tree_leaves(got_tree),
                    jax.tree_util.tree_leaves(want_tree)):
        e, ok = _cmp(g, w, tol)
        errs.append(e)
        oks.append(ok)
    return max(errs), all(oks)


# --------------------------------------------------------------- checks


def check_adam_flat(jax, jnp):
    from apex_tpu.ops.pallas.fused_adam_kernel import (
        ADAM_MODE_L2, fused_adam_flat, fused_adam_flat_master)
    from apex_tpu.optimizers.functional import adam_update

    n = 8 * 1024 if SMALL else 64 * 1024
    key = jax.random.PRNGKey(0)
    p = jax.random.normal(key, (n,), jnp.bfloat16)
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.bfloat16)
    m = jax.random.normal(jax.random.PRNGKey(2), (n,)) * 0.1
    v = jnp.abs(jax.random.normal(jax.random.PRNGKey(3), (n,))) * 0.01
    kw = dict(lr=1e-3, weight_decay=0.01, step=3, inv_scale=0.5)
    out = {}
    # adamw mode
    pn, mn, vn = fused_adam_flat(p.copy(), g, m.copy(), v.copy(), **kw)
    rp, rm, rv = adam_update(p, g, m, v, **kw)
    e1, ok1 = _tree_cmp((pn, mn, vn), (rp, rm, rv), 2e-2)
    # L2 mode
    pn2, mn2, vn2 = fused_adam_flat(p.copy(), g, m.copy(), v.copy(), mode=ADAM_MODE_L2, **kw)
    rp2, rm2, rv2 = adam_update(p, g, m, v, adam_w_mode=False, **kw)
    e2, ok2 = _tree_cmp((pn2, mn2, vn2), (rp2, rm2, rv2), 2e-2)
    # found_inf skip must be exact
    pn3, mn3, vn3 = fused_adam_flat(p.copy(), g, m.copy(), v.copy(), found_inf=True, **kw)
    e3, ok3 = _tree_cmp((pn3, mn3, vn3), (p, m, v), 0.0)
    # master variant
    pm = p.astype(jnp.float32)
    pmn, plp, mn4, vn4 = fused_adam_flat_master(pm.copy(), g, m.copy(), v.copy(), **kw)
    rpm, rmm, rvm = adam_update(pm, g, m, v, **kw)
    e4, ok4 = _tree_cmp((pmn, mn4, vn4), (rpm, rmm, rvm), 1e-5)
    e5, ok5 = _cmp(plp, rpm.astype(jnp.bfloat16), 1e-2)
    return {"max_err": max(e1, e2, e3, e4, e5),
            "pass": ok1 and ok2 and ok3 and ok4 and ok5}


def check_sgd_flat(jax, jnp):
    from apex_tpu.ops.pallas.fused_sgd_kernel import fused_sgd_flat
    from apex_tpu.optimizers.functional import sgd_update

    n = 8 * 1024 if SMALL else 64 * 1024
    p = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.bfloat16)
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.bfloat16)
    buf = jax.random.normal(jax.random.PRNGKey(2), (n,)) * 0.1
    errs, oks = [], []
    for kw in (dict(momentum=0.9, weight_decay=1e-4),
               dict(momentum=0.9, nesterov=True),
               dict(momentum=0.9, weight_decay=1e-4, wd_after_momentum=True),
               dict(momentum=0.9, first_step=True)):
        pn, bn = fused_sgd_flat(p.copy(), g, buf.copy(), lr=0.1, inv_scale=2.0, **kw)
        rp, rb = sgd_update(p, g, buf, lr=0.1, inv_scale=2.0, **kw)
        e, ok = _tree_cmp((pn, bn), (rp, rb), 2e-2)
        errs.append(e)
        oks.append(ok)
    return {"max_err": max(errs), "pass": all(oks)}


def _opt_tree(jax, jnp):
    shapes = [(300,), (17, 129), (64, 64), (1000,)]
    p = [jax.random.normal(jax.random.PRNGKey(i), s) * 0.5
         for i, s in enumerate(shapes)]
    g = [jax.random.normal(jax.random.PRNGKey(10 + i), s)
         for i, s in enumerate(shapes)]
    return p, g


def check_lamb_flat(jax, jnp):
    from apex_tpu.ops.pallas.fused_opt_kernels import (fused_lamb_flat,
                                                       row_segment_ids)
    from apex_tpu.optimizers.functional import lamb_update
    from apex_tpu.utils.flatten import flat_spec, flatten, unflatten

    p, g = _opt_tree(jax, jnp)
    m = [jnp.zeros_like(x) for x in p]
    v = [jnp.zeros_like(x) for x in p]
    spec = flat_spec(p)
    fp = flatten(p, spec, dtype=jnp.float32, pad_to=1024)
    fg = flatten(g, spec, dtype=jnp.float32, pad_to=fp.size)
    fm = jnp.zeros_like(fp)
    fv = jnp.zeros_like(fp)
    rid = row_segment_ids(spec, fp.size)
    kw = dict(lr=1e-2, weight_decay=0.01, step=2, max_grad_norm=1.0)
    pn, mn, vn, gnorm = fused_lamb_flat(fp.copy(), fg, fm.copy(), fv.copy(), rid,
                                        num_tensors=spec.num_leaves, **kw)
    rp, rm, rv, rnorm = lamb_update(p, g, m, v, **kw)
    e1, ok1 = _tree_cmp(unflatten(pn, spec), rp, 1e-4)
    e2, ok2 = _cmp(gnorm, rnorm, 1e-5)
    return {"max_err": max(e1, e2), "pass": ok1 and ok2}


def check_novograd_flat(jax, jnp):
    from apex_tpu.ops.pallas.fused_opt_kernels import (fused_novograd_flat,
                                                       row_segment_ids)
    from apex_tpu.optimizers.functional import novograd_update
    from apex_tpu.utils.flatten import flat_spec, flatten, unflatten

    p, g = _opt_tree(jax, jnp)
    m = [jnp.zeros_like(x) for x in p]
    spec = flat_spec(p)
    fp = flatten(p, spec, dtype=jnp.float32, pad_to=1024)
    fg = flatten(g, spec, dtype=jnp.float32, pad_to=fp.size)
    fm = jnp.zeros_like(fp)
    rid = row_segment_ids(spec, fp.size)
    vt = jnp.zeros((spec.num_leaves,), jnp.float32)
    kw = dict(lr=1e-2, weight_decay=0.01, step=1)
    pn, mn, vn = fused_novograd_flat(fp.copy(), fg, fm.copy(), vt.copy(),
                                     rid, num_tensors=spec.num_leaves, **kw)
    # functional novograd keeps v as per-tensor tree of scalars
    rp, rm, rv = novograd_update(p, g, m, [jnp.float32(0.0)] * len(p), **kw)
    e1, ok1 = _tree_cmp(unflatten(pn, spec), rp, 1e-4)
    e2, ok2 = _tree_cmp(list(vn), rv, 1e-4)
    return {"max_err": max(e1, e2), "pass": ok1 and ok2}


def check_adagrad_flat(jax, jnp):
    from apex_tpu.ops.pallas.fused_opt_kernels import fused_adagrad_flat
    from apex_tpu.optimizers.functional import adagrad_update

    n = 8 * 1024 if SMALL else 64 * 1024
    p = jax.random.normal(jax.random.PRNGKey(0), (n,))
    g = jax.random.normal(jax.random.PRNGKey(1), (n,))
    h = jnp.abs(jax.random.normal(jax.random.PRNGKey(2), (n,))) * 0.1
    kw = dict(lr=1e-2, weight_decay=1e-4)
    pn, hn = fused_adagrad_flat(p.copy(), g, h.copy(), **kw)
    rp, rh = adagrad_update(p, g, h, **kw)
    return dict(zip(("max_err", "pass"),
                    _tree_cmp((pn, hn), (rp, rh), 1e-5)))


def _ln_ref(jnp, x, w, b, eps=1e-5, rms=False):
    x32 = x.astype(jnp.float32)
    if rms:
        ms = jnp.mean(x32 * x32, -1, keepdims=True)
        y = x32 * jax_lax_rsqrt(jnp, ms + eps)
    else:
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.mean((x32 - mu) ** 2, -1, keepdims=True)
        y = (x32 - mu) * jax_lax_rsqrt(jnp, var + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y


def jax_lax_rsqrt(jnp, x):
    return 1.0 / jnp.sqrt(x)


def check_layer_norm(jax, jnp):
    from apex_tpu.normalization.fused_layer_norm import (
        fused_layer_norm_affine, fused_rms_norm_affine)

    rows, hidden = (64, 256) if SMALL else (512, 1024)
    x = jax.random.normal(jax.random.PRNGKey(0), (rows, hidden))
    w = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(1), (hidden,))
    b = 0.1 * jax.random.normal(jax.random.PRNGKey(2), (hidden,))
    errs, oks = [], []
    for mem_eff in (False, True):
        y = fused_layer_norm_affine(x, w, b, hidden,
                                    memory_efficient=mem_eff)
        e, ok = _cmp(y, _ln_ref(jnp, x, w, b), 1e-4)
        errs.append(e)
        oks.append(ok)

        def loss(fn):
            return lambda x, w, b: jnp.sum(fn(x, w, b) ** 2)

        gf = jax.grad(
            lambda x, w, b: jnp.sum(fused_layer_norm_affine(
                x, w, b, hidden, memory_efficient=mem_eff) ** 2),
            argnums=(0, 1, 2))(x, w, b)
        gr = jax.grad(
            lambda x, w, b: jnp.sum(_ln_ref(jnp, x, w, b) ** 2),
            argnums=(0, 1, 2))(x, w, b)
        e, ok = _tree_cmp(gf, gr, 2e-3)
        errs.append(e)
        oks.append(ok)
        # RMSNorm
        yr = fused_rms_norm_affine(x, w, hidden, memory_efficient=mem_eff)
        e, ok = _cmp(yr, _ln_ref(jnp, x, w, None, rms=True), 1e-4)
        errs.append(e)
        oks.append(ok)
    # bf16 io
    xb = x.astype(jnp.bfloat16)
    yb = fused_layer_norm_affine(xb, w, b, hidden)
    e, ok = _cmp(yb, _ln_ref(jnp, xb, w, b).astype(jnp.bfloat16), 2e-2)
    errs.append(e)
    oks.append(ok)
    return {"max_err": max(errs), "pass": all(oks)}


def check_group_norm(jax, jnp):
    from apex_tpu.ops.pallas.group_norm_kernel import group_norm_nhwc_pallas

    n, h, w_, c, g = (1, 4, 4, 128, 16) if SMALL else (2, 8, 8, 256, 32)
    x = jax.random.normal(jax.random.PRNGKey(0), (n, h, w_, c))
    wt = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(1), (c,))
    bs = 0.1 * jax.random.normal(jax.random.PRNGKey(2), (c,))
    errs, oks = [], []
    for act in ("", "silu"):
        for algo in ("one_pass", "two_pass"):
            y, mean, rstd = group_norm_nhwc_pallas(x, g, wt, bs, act=act,
                                                   algo=algo)
            x5 = x.reshape(n, h * w_, g, c // g).astype(jnp.float32)
            mu = jnp.mean(x5, axis=(1, 3), keepdims=True)
            var = jnp.mean((x5 - mu) ** 2, axis=(1, 3), keepdims=True)
            yr = ((x5 - mu) / jnp.sqrt(var + 1e-5)).reshape(n, h, w_, c)
            yr = yr * wt + bs
            if act == "silu":
                yr = yr * jax.nn.sigmoid(yr)
            e, ok = _cmp(y, yr, 1e-4)
            errs.append(e)
            oks.append(ok)
    return {"max_err": max(errs), "pass": all(oks)}


def check_softmax(jax, jnp):
    from apex_tpu.ops.pallas.softmax_kernel import (softmax_bwd_pallas,
                                                    softmax_fwd_pallas)

    B, sq, sk = (2, 128, 128) if SMALL else (8, 256, 256)
    x = jax.random.normal(jax.random.PRNGKey(0), (B, sq, sk))
    errs, oks = [], []
    # causal
    y = softmax_fwd_pallas(x, None, scale=0.5, causal=True)
    mask = jnp.tril(jnp.ones((sq, sk), bool))
    ref = jax.nn.softmax(jnp.where(mask, x * 0.5, -1e30), axis=-1)
    e, ok = _cmp(y, ref, 1e-5)
    errs.append(e)
    oks.append(ok)
    # arbitrary mask (True = masked), per-batch shared across heads
    m3 = jax.random.bernoulli(jax.random.PRNGKey(1), 0.3, (B, sq, sk))
    ym = softmax_fwd_pallas(x, m3, scale=0.7, causal=False)
    refm_logits = jnp.where(m3, -1e30, x * 0.7)
    refm = jax.nn.softmax(refm_logits, axis=-1)
    # fully-masked rows yield zeros (megatron convention)
    all_masked = jnp.all(m3, axis=-1, keepdims=True)
    refm = jnp.where(all_masked, 0.0, refm)
    e, ok = _cmp(ym, refm, 1e-5)
    errs.append(e)
    oks.append(ok)
    # backward: dx = y * (dy - sum(dy * y)) * scale
    dy = jax.random.normal(jax.random.PRNGKey(2), (B, sq, sk))
    dx = softmax_bwd_pallas(y, dy, scale=0.5)
    dref = y * (dy - jnp.sum(dy * y, -1, keepdims=True)) * 0.5
    e, ok = _cmp(dx, dref, 1e-5)
    errs.append(e)
    oks.append(ok)
    return {"max_err": max(errs), "pass": all(oks)}


def _flash_ref(jax, jnp, q, k, v, causal=False, mask=None, scale=None):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    sq, sk = logits.shape[-2:]
    if causal:
        # top-left aligned: query i attends keys j <= i (kernel convention,
        # matching the megatron upper-triang softmax)
        cm = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, -1e30, logits)
    p = jax.nn.softmax(logits, axis=-1)
    if mask is not None:
        # fully-masked rows yield zero output (megatron generic-masked
        # softmax convention, matched by the flash kernel)
        fully = jnp.all(jnp.broadcast_to(mask, logits.shape), axis=-1,
                        keepdims=True)
        p = jnp.where(fully, 0.0, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def check_flash_attention(jax, jnp):
    from apex_tpu.ops.pallas.flash_attention import flash_attention

    b, h, s, d = (1, 1, 128, 64) if SMALL else (1, 2, 256, 64)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d)) * 0.3 for kk in ks)
    errs, oks = [], []
    # causal fwd
    y = flash_attention(q, k, v, True)
    ref = _flash_ref(jax, jnp, q, k, v, causal=True)
    e, ok = _cmp(y, ref, 2e-3)
    errs.append(e)
    oks.append(ok)
    # causal bwd
    gf = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, True) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        _flash_ref(jax, jnp, q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    e, ok = _tree_cmp(gf, gr, 5e-3)
    errs.append(e)
    oks.append(ok)
    # arbitrary mask fwd+bwd
    mask = jax.random.bernoulli(jax.random.PRNGKey(5), 0.25,
                                (b, 1, s, s))
    ym = flash_attention(q, k, v, mask=mask)
    refm = _flash_ref(jax, jnp, q, k, v, mask=mask)
    e, ok = _cmp(ym, refm, 2e-3)
    errs.append(e)
    oks.append(ok)
    gfm = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, mask=mask) ** 2))(q)
    grm = jax.grad(lambda q: jnp.sum(
        _flash_ref(jax, jnp, q, k, v, mask=mask) ** 2))(q)
    e, ok = _cmp(gfm, grm, 5e-3)
    errs.append(e)
    oks.append(ok)
    # ragged (non-multiple-of-block) lengths
    r1, r2 = (65, 93) if SMALL else (193, 217)
    qs, kss, vs = q[:, :, :r1], k[:, :, :r2], v[:, :, :r2]
    yr = flash_attention(qs, kss, vs, True)
    refr = _flash_ref(jax, jnp, qs, kss, vs, causal=True)
    e, ok = _cmp(yr, refr, 2e-3)
    errs.append(e)
    oks.append(ok)
    # dropout: deterministic per seed, differing across seeds, unbiased-ish
    y1 = flash_attention(q, k, v, True, dropout_p=0.3, dropout_seed=7)
    y2 = flash_attention(q, k, v, True, dropout_p=0.3, dropout_seed=7)
    y3 = flash_attention(q, k, v, True, dropout_p=0.3, dropout_seed=8)
    e, same = _cmp(y1, y2, 0.0)
    errs.append(e)
    oks.append(same)
    import numpy as np

    oks.append(bool(np.any(np.asarray(y1) != np.asarray(y3))))
    # dropout bwd executes (and is finite)
    gd = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, True, dropout_p=0.3, dropout_seed=7) ** 2))(q)
    oks.append(bool(np.all(np.isfinite(np.asarray(gd)))))
    return {"max_err": max(errs), "pass": all(oks)}


def check_remote_copy(jax, jnp):
    """Compile coverage for the Pallas remote-DMA kernels on a 1-device
    mesh: a self-ring peer_shift must be the identity, and the
    non-periodic halo exchange must return zero halos (the single device
    is both ring edges). Exercises make_async_remote_copy + DMA-semaphore
    lowering on the real chip (the multi-device semantics are
    parity-tested on the virtual CPU mesh)."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.ops.pallas.remote_copy import (halo_exchange_rdma,
                                                 peer_shift)
    from apex_tpu.utils.compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 256), jnp.float32)

    def body(x):
        y = peer_shift(x, "x", 1)
        lo, hi = halo_exchange_rdma(x, "x", 2)
        return y, lo, hi

    y, lo, hi = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("x"), out_specs=(P("x"), P("x"),
                                                     P("x")),
        check_vma=False))(x)
    e1, ok1 = _cmp(y, x, 0.0)
    e2, ok2 = _cmp(lo, jnp.zeros_like(lo), 0.0)
    e3, ok3 = _cmp(hi, jnp.zeros_like(hi), 0.0)

    # pool-backed landing buffers: the same exchange with donated
    # input/output-aliased buffers (PeerMemoryPool flow) must agree —
    # compiles the aliasing path on the real chip
    from apex_tpu.ops.pallas.remote_copy import halo_buf_rows

    br = halo_buf_rows(16, 2, x.dtype)
    bufs = (jnp.zeros((br, 256), x.dtype), jnp.zeros((br, 256), x.dtype))

    def body_pool(x, lo_in, hi_in):
        return halo_exchange_rdma(x, "x", 2, bufs=(lo_in, hi_in))

    lo2, hi2 = jax.jit(shard_map(
        body_pool, mesh=mesh, in_specs=(P("x"), P("x"), P("x")),
        out_specs=(P("x"), P("x")), check_vma=False))(x, *bufs)
    e4, ok4 = _cmp(lo2, jnp.zeros_like(lo2), 0.0)
    e5, ok5 = _cmp(hi2, jnp.zeros_like(hi2), 0.0)
    return {"max_err": max(e1, e2, e3, e4, e5),
            "pass": ok1 and ok2 and ok3 and ok4 and ok5}


CHECKS = [
    ("fused_adam_flat", check_adam_flat),
    ("fused_sgd_flat", check_sgd_flat),
    ("fused_lamb_flat", check_lamb_flat),
    ("fused_novograd_flat", check_novograd_flat),
    ("fused_adagrad_flat", check_adagrad_flat),
    ("layer_norm", check_layer_norm),
    ("group_norm", check_group_norm),
    ("softmax", check_softmax),
    ("flash_attention", check_flash_attention),
    ("remote_copy", check_remote_copy),
]


def run_checks(jax, jnp, backend: str, out_path: str | None = None) -> dict:
    """Run every check against an ALREADY-initialized backend, writing the
    results dict to ``out_path`` incrementally (rewritten after each check,
    so a mid-run crash still leaves a partial artifact). Separated from
    main() so the background chip worker (tools/chip_worker.py) can invoke
    the checks in-process without re-probing the relay."""
    global SMALL
    SMALL = backend != "tpu"  # interpret-mode smoke: keep shapes tiny

    from bench import atomic_write_json

    results = {"backend": backend,
               "chip": os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
               if backend == "tpu" else backend,
               "compiled": backend == "tpu",
               "complete": False, "ok": False}
    all_ok = True
    for name, fn in CHECKS:
        t0 = time.perf_counter()
        try:
            r = fn(jax, jnp)
        except Exception as e:
            r = {"pass": False, "error": f"{type(e).__name__}: {e}"}
        r["wall_s"] = round(time.perf_counter() - t0, 1)
        results[name] = r
        all_ok = all_ok and r.get("pass", False)
        print(f"[chipcheck] {name}: "
              f"{'PASS' if r.get('pass') else 'FAIL'} {r}",
              file=sys.stderr, flush=True)
        if out_path is not None:
            # "ok" stays False until EVERY check has run — a mid-run crash
            # must not leave an artifact claiming overall success
            atomic_write_json(out_path, results)
    results["complete"] = True
    results["ok"] = bool(all_ok and backend == "tpu")
    if out_path is not None:
        atomic_write_json(out_path, results)
    return results


def main():
    jax, backend = _acquire_backend()
    import jax.numpy as jnp

    here = os.path.dirname(os.path.abspath(__file__))
    # smoke runs must not clobber the on-chip acceptance artifact
    name = ("CHIPCHECK_SMOKE.json" if backend != "tpu"
            else "CHIPCHECK.json")
    results = run_checks(jax, jnp, backend,
                         out_path=os.path.join(here, name))
    print(json.dumps({"ok": results["ok"], "backend": backend,
                      "passed": sum(1 for n, _ in CHECKS
                                    if results[n].get("pass")),
                      "total": len(CHECKS)}))
    if not results["ok"]:
        sys.exit(2)


if __name__ == "__main__":
    main()
