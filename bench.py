"""Benchmark driver — prints ONE JSON line (the headline metric) to stdout
and writes the full suite to BENCH_SUITE.json.

Headline (BASELINE.json row 1): fused Adam step latency at 1B params on one
TPU chip, via the flat-buffer Pallas kernel
(apex_tpu/ops/pallas/fused_adam_kernel.py) — the TPU equivalent of the
reference's ``multi_tensor_adam`` launch path (csrc/multi_tensor_adam.cu:24
via csrc/multi_tensor_apply.cuh:32-103). Dtype mix matches the reference's
mixed-precision setup: bf16 params + bf16 grads + fp32 exp_avg/exp_avg_sq
(fused_adam.py:212-232 groups). The op is HBM-bound: 22 bytes/element.

Suite (BASELINE.md configs 2-5 coverage, VERDICT item 2):
- ``fused_adam_1b``: the headline.
- ``layer_norm``: Pallas LN fwd+bwd (csrc/layer_norm_cuda_kernel.cu path).
- ``flash_attention``: causal flash fwd+bwd (megatron softmax + MHA path).
- ``resnet50_train``: one jitted ResNet-50 train step (fwd+bwd+FusedAdam),
  imgs/sec/chip — the north-star recipe of tests/L1 (main_amp.py).

``vs_baseline``: measured A100-class estimate for the same op (HBM-bandwidth
model at 1555 GB/s · 85% achievable for memory-bound ops; published MLPerf
A100 throughput for ResNet-50). >1 ⇒ faster than the A100 reference path.
``hbm_frac`` (suite): fraction of this chip's HBM peak the op achieved.

On non-TPU hosts (CI smoke) tiny shapes keep interpret-mode runtime sane; the
driver runs this on the real chip.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# per-generation peaks for achieved-fraction reporting (bf16 TFLOPs, GB/s)
_CHIP = {
    "v5e": {"hbm_gbps": 819.0, "tflops": 197.0},
    "v6e": {"hbm_gbps": 1640.0, "tflops": 918.0},
    "v5p": {"hbm_gbps": 2765.0, "tflops": 459.0},
}
_A100_GBPS = 1555e9 * 0.85  # apex multi_tensor kernels reach ~85% of peak


def _backend_with_timeout(seconds: int = 180):
    """Initialize the JAX backend, guarding against a wedged TPU relay (the
    axon sitecustomize initializes the TPU client on ANY backend request and
    can hang indefinitely if a previous holder died mid-claim; the hang sits
    in C so in-process alarms can't interrupt it). Probe in a subprocess with
    a hard timeout; if the probe hangs, re-exec this script on pure CPU
    (axon hook stripped) so the driver still gets a JSON line."""
    if os.environ.get("APEX_TPU_BENCH_CPU") != "1":
        # SIGTERM (not SIGKILL) on timeout so the probe can release its TPU
        # claim cleanly — a hard kill mid-claim would itself wedge the relay
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        try:
            ok = proc.wait(timeout=seconds) == 0
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
            ok = False
        if not ok:
            from __graft_entry__ import sanitized_cpu_env
            env = sanitized_cpu_env()
            env["APEX_TPU_BENCH_CPU"] = "1"
            os.execve(sys.executable, [sys.executable, __file__], env)

    import jax

    return jax, jax.default_backend()


def _timed(fn, *args, iters=20, warmup=2):
    import jax

    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def bench_fused_adam(jax, jnp, on_tpu, chip):
    n = (1_000_000_000 if on_tpu else 1_048_576) // 1024 * 1024
    from apex_tpu.ops.pallas.fused_adam_kernel import fused_adam_flat

    p = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.bfloat16) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.bfloat16)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)

    state = [p, m, v]

    def step(s):
        return fused_adam_flat(state[0], g, state[1], state[2], lr=1e-3,
                               weight_decay=0.01, step=s, inv_scale=1.0)

    # warmup / compile (donation: rebind buffers each call)
    state = list(step(jnp.int32(1)))
    jax.block_until_ready(state[0])
    iters = 20 if on_tpu else 2
    t0 = time.perf_counter()
    for i in range(iters):
        state = list(step(jnp.int32(2 + i)))
    jax.block_until_ready(state[0])
    ms = (time.perf_counter() - t0) / iters * 1e3

    bytes_moved = n * 22  # r: p2+g2+m4+v4, w: p2+m4+v4
    ref_ms = bytes_moved / _A100_GBPS * 1e3
    return {
        "metric": f"fused_adam_step_ms_at_{n // 1_000_000}M_params_"
                  f"bf16p_f32state",
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": round(ref_ms / ms, 3),
        "hbm_frac": round(bytes_moved / (ms / 1e3) / 1e9
                          / chip["hbm_gbps"], 3),
    }


def bench_layer_norm(jax, jnp, on_tpu, chip):
    rows, cols = (8192, 4096) if on_tpu else (256, 512)
    from apex_tpu.normalization.fused_layer_norm import \
        fused_layer_norm_affine

    x = jax.random.normal(jax.random.PRNGKey(0), (rows, cols), jnp.bfloat16)
    w = jnp.ones((cols,), jnp.float32)
    b = jnp.zeros((cols,), jnp.float32)

    fwd = jax.jit(lambda x: fused_layer_norm_affine(x, w, b, cols))
    ms_fwd = _timed(fwd, x, iters=20 if on_tpu else 2)

    grad = jax.jit(jax.grad(
        lambda x: jnp.sum(fused_layer_norm_affine(x, w, b, cols) ** 2)))
    ms_bwd = _timed(grad, x, iters=20 if on_tpu else 2)

    n = rows * cols
    ref_fwd = (n * 4) / _A100_GBPS * 1e3  # r2 + w2 bytes
    return {
        "metric": f"layer_norm_fwd_ms_{rows}x{cols}_bf16",
        "value": round(ms_fwd, 3), "unit": "ms",
        "bwd_ms": round(ms_bwd, 3),
        "vs_baseline": round(ref_fwd / ms_fwd, 3),
        "hbm_frac": round((n * 4) / (ms_fwd / 1e3) / 1e9
                          / chip["hbm_gbps"], 3),
    }


def bench_flash_attention(jax, jnp, on_tpu, chip):
    b, h, s, d = (4, 16, 2048, 64) if on_tpu else (1, 2, 256, 64)
    from apex_tpu.ops.pallas.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(k_, (b, h, s, d), jnp.bfloat16) * 0.2
               for k_ in ks)
    fwd = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))
    ms_fwd = _timed(fwd, q, k, v, iters=10 if on_tpu else 2)
    grad = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, True)
                                .astype(jnp.float32) ** 2), (0, 1, 2)))
    ms_bwd = _timed(grad, q, k, v, iters=10 if on_tpu else 2)

    # causal: 2 matmuls over s²/2 valid positions
    flops = 2 * 2 * b * h * s * s * d / 2
    tflops = flops / (ms_fwd / 1e3) / 1e12
    # A100 bf16 peak 312 TFLOPs; flash-attn fwd typically ~60% of peak
    ref_ms = flops / (312e12 * 0.6) * 1e3
    return {
        "metric": f"flash_attention_causal_fwd_ms_b{b}h{h}s{s}d{d}",
        "value": round(ms_fwd, 3), "unit": "ms",
        "bwd_ms": round(ms_bwd, 3),
        "vs_baseline": round(ref_ms / ms_fwd, 3),
        "tflops": round(tflops, 1),
        "mxu_frac": round(tflops / chip["tflops"], 3),
    }


def bench_resnet50(jax, jnp, on_tpu, chip):
    import numpy as np

    from apex_tpu.models.resnet import ResNet18ish, ResNet50
    from apex_tpu.optimizers.functional import adam_update

    if on_tpu:
        model, batch, hw = ResNet50(), 128, 224
    else:
        model, batch, hw = ResNet18ish(), 8, 32
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, hw, hw, 3),
                          jnp.bfloat16)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0,
                           1000 if on_tpu else 10, jnp.int32)
    variables = model.init(jax.random.PRNGKey(2), x)
    params, bstats = variables["params"], variables["batch_stats"]
    m0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                                params)
    v0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                                params)

    @jax.jit
    def train_step(params, m, v, bstats, x, y, step):
        def loss_fn(p):
            logits, updated = model.apply(
                {"params": p, "batch_stats": bstats}, x,
                mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, logits.shape[-1])
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * onehot, axis=-1))
            return loss, updated["batch_stats"]

        (loss, bs2), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        params, m, v = adam_update(params, grads, m, v, step=step,
                                   lr=1e-3, weight_decay=1e-4)
        return params, m, v, bs2, loss

    def step_wrap(params, m, v, x, y, s):
        nonlocal bstats
        params, m, v, bstats, loss = train_step(params, m, v, bstats, x,
                                                y, s)
        return params, m, v, loss

    train_step_run = step_wrap
    state = (params, m0, v0)
    state = train_step_run(*state, x, y, jnp.int32(1))[:3]
    jax.block_until_ready(state[0])
    iters = 10 if on_tpu else 2
    t0 = time.perf_counter()
    for i in range(iters):
        out = train_step_run(*state, x, y, jnp.int32(2 + i))
        state = out[:3]
    jax.block_until_ready(state[0])
    ms = (time.perf_counter() - t0) / iters * 1e3
    imgs_sec = batch / (ms / 1e3)
    # MLPerf-class A100 ResNet-50 ≈ 2900 imgs/sec/GPU (amp, DALI input)
    ref = 2900.0 if on_tpu else float("nan")
    entry = {
        "metric": f"resnet50_train_imgs_per_sec_b{batch}_{hw}px"
                  if on_tpu else
                  f"resnet18ish_train_imgs_per_sec_b{batch}_{hw}px",
        "value": round(imgs_sec, 1), "unit": "imgs/sec",
        "step_ms": round(ms, 2),
    }
    if on_tpu:
        entry["vs_baseline"] = round(imgs_sec / ref, 3)
    else:
        entry["vs_baseline"] = 0.0
    return entry


def main():
    jax, backend = _backend_with_timeout()
    import jax.numpy as jnp

    on_tpu = backend == "tpu"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    chip = _CHIP.get(gen, _CHIP["v5e"])

    suite = {"backend": backend, "chip": gen if on_tpu else "cpu-smoke"}
    headline = None
    benches = [("fused_adam_1b", bench_fused_adam),
               ("layer_norm", bench_layer_norm),
               ("flash_attention", bench_flash_attention),
               ("resnet50_train", bench_resnet50)]
    for name, fn in benches:
        try:
            t0 = time.perf_counter()
            entry = fn(jax, jnp, on_tpu, chip)
            entry["bench_wall_s"] = round(time.perf_counter() - t0, 1)
            suite[name] = entry
            print(f"[bench] {name}: {entry}", file=sys.stderr)
        except Exception as e:  # a failing sub-bench must not kill the line
            suite[name] = {"error": f"{type(e).__name__}: {e}"}
            print(f"[bench] {name} FAILED: {e}", file=sys.stderr)
        if name == "fused_adam_1b" and "error" not in suite[name]:
            headline = suite[name]

    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_SUITE.json"), "w") as f:
        json.dump(suite, f, indent=1)

    if headline is None:  # headline failed: emit an honest failure line
        headline = {"metric": "fused_adam_step_ms", "value": -1.0,
                    "unit": "ms", "vs_baseline": 0.0}
    print(json.dumps({k: headline[k] for k in
                      ("metric", "value", "unit", "vs_baseline")}))


if __name__ == "__main__":
    main()
