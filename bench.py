"""Benchmark driver — prints ONE JSON line (the headline metric) to stdout
and writes the full suite to BENCH_SUITE.json.

Headline (BASELINE.json row 1): fused Adam step latency at 1B params on one
TPU chip, via the flat-buffer Pallas kernel
(apex_tpu/ops/pallas/fused_adam_kernel.py) — the TPU equivalent of the
reference's ``multi_tensor_adam`` launch path (csrc/multi_tensor_adam.cu:24
via csrc/multi_tensor_apply.cuh:32-103). Dtype mix matches the reference's
mixed-precision setup: bf16 params + bf16 grads + fp32 exp_avg/exp_avg_sq
(fused_adam.py:212-232 groups). The op is HBM-bound: 22 bytes/element.

Timing methodology: K chained steps inside ONE jitted ``lax.fori_loop`` with
donated state, completion forced by a host fetch of one output element
(apex_tpu/utils/benchtime.py). Wall-clock around individual dispatches is
meaningless on the tunneled runtime — ``block_until_ready`` returns before
remote execution completes — and the loop form is also the honest analog of
the reference's CUDA-graph "capturable" mode (one launch, K steps).

Suite (BASELINE.md configs 2-5 coverage):
- ``fused_adam_1b``: the headline.
- ``layer_norm``: Pallas LN fwd/bwd (csrc/layer_norm_cuda_kernel.cu path).
- ``flash_attention``: causal flash fwd/bwd (megatron softmax + MHA path).
- ``resnet50_train``: one jitted ResNet-50 train step (fwd+bwd+FusedAdam),
  imgs/sec/chip — the north-star recipe of tests/L1 (main_amp.py).

``vs_baseline``: measured-time ratio vs an A100-class estimate for the same
op (HBM-bandwidth model at 1555 GB/s · 85% achievable for memory-bound ops;
published MLPerf A100 throughput for ResNet-50). >1 ⇒ faster than the A100
reference path. NOTE: a v5e has 819 GB/s HBM vs an A100's 1555 — for
HBM-bound ops the chip-fair comparison is ``hbm_frac`` (fraction of this
chip's peak achieved) vs the reference kernels' ~85%-of-A100-peak; and
``efficiency_vs_ref`` = hbm_frac / 0.85 reports exactly that ratio.

On non-TPU hosts (CI smoke) tiny shapes keep interpret-mode runtime sane; the
driver runs this on the real chip.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# per-generation peaks for achieved-fraction reporting (bf16 TFLOPs, GB/s)
_CHIP = {
    "v5e": {"hbm_gbps": 819.0, "tflops": 197.0},
    "v6e": {"hbm_gbps": 1640.0, "tflops": 918.0},
    "v5p": {"hbm_gbps": 2765.0, "tflops": 459.0},
}
_A100_GBPS = 1555e9 * 0.85  # apex multi_tensor kernels reach ~85% of peak


# timed-out probe children, left to finish on their own (reaped lazily)
_orphan_probes = []


def _probe_once(seconds: int) -> bool:
    """One subprocess backend probe under a hard timeout.

    CAUTION: the probe is NOT claim-free — the axon sitecustomize
    initializes the TPU client on ANY backend request, so a timed-out
    probe may itself hold a partial claim. A hung child is blocked in C
    (SIGTERM's handler would never run — and if it DID land mid-claim it
    would wedge the relay for hours, the exact failure this module exists
    to survive). So on timeout we send NO signal and do NOT block: orphan
    the child to finish at its own pace, return False, and keep the
    caller's deadline live."""
    # reap any earlier orphans that have since finished
    _orphan_probes[:] = [p for p in _orphan_probes if p.poll() is None]
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.default_backend())"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        return proc.wait(timeout=seconds) == 0
    except subprocess.TimeoutExpired:
        _orphan_probes.append(proc)
        return False


def wait_for_backend(probe_s: int = 180, total_s: int = 2100,
                     tag: str = "bench") -> bool:
    """Probe the backend PATIENTLY — every few minutes for up to ``total_s``
    (~35 min) — and return True once a probe succeeds, False when patience
    runs out. A wedged relay CLEARS on its own after the stale claim
    expires, so a single probe throwing away the round is the failure mode
    that burned rounds 1-2. Shared by bench.py and chipcheck.py."""
    deadline = time.monotonic() + total_s
    attempt = 0
    while True:
        attempt += 1
        t0 = time.monotonic()
        if _probe_once(probe_s):
            return True
        print(f"[{tag}] backend probe {attempt} failed "
              f"({time.monotonic() - t0:.0f}s); relay may be wedged — "
              f"{max(0.0, deadline - time.monotonic()):.0f}s of patience "
              "left", file=sys.stderr, flush=True)
        if time.monotonic() >= deadline:
            return False
        time.sleep(60)


def _backend_with_timeout(probe_s: int = 180, total_s: int = 2100):
    """Initialize the JAX backend, guarding against a wedged TPU relay (the
    axon sitecustomize initializes the TPU client on ANY backend request and
    can hang indefinitely if a previous holder died mid-claim; the hang sits
    in C so in-process alarms can't interrupt it). Patient probing via
    :func:`wait_for_backend`; on exhaustion fall back to pure CPU —
    LOUDLY: main() puts ``"backend"`` in the headline JSON line and exits
    nonzero, so a driver-captured record that missed the chip is
    unmistakable."""
    if os.environ.get("APEX_TPU_BENCH_CPU") != "1":
        if not wait_for_backend(probe_s, total_s):
            from __graft_entry__ import sanitized_cpu_env
            env = sanitized_cpu_env()
            env["APEX_TPU_BENCH_CPU"] = "1"
            os.execve(sys.executable, [sys.executable, __file__], env)

    import jax

    return jax, jax.default_backend()


def bench_fused_adam(jax, jnp, on_tpu, chip, floor_s):
    from apex_tpu.ops.pallas.fused_adam_kernel import LANE, fused_adam_flat
    from apex_tpu.utils.benchtime import timed_steps

    n = (999_999_488 if on_tpu else 1_048_576)
    rows = n // LANE
    # state lives as (rows, 128) — the kernel's native tiling — so no
    # relayout copy sits between steps (a 1-D->2-D copy of fp32 state is
    # 7.4 GB and OOMs the 1B case)
    p = jax.random.normal(jax.random.PRNGKey(0), (rows, LANE),
                          jnp.bfloat16) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (rows, LANE), jnp.bfloat16)
    m = jnp.zeros((rows, LANE), jnp.float32)
    v = jnp.zeros((rows, LANE), jnp.float32)

    def step(i, st, g):
        p, m, v = st
        p, m, v = fused_adam_flat(p, g, m, v, lr=1e-3, weight_decay=0.01,
                                  step=i + 1, inv_scale=1.0)
        return (p, m, v)

    ms = timed_steps(step, (p, m, v), iters=30 if on_tpu else 2,
                     consts=(g,), floor_s=floor_s)
    bytes_moved = n * 22  # r: p2+g2+m4+v4, w: p2+m4+v4
    ref_ms = bytes_moved / _A100_GBPS * 1e3
    hbm_frac = bytes_moved / (ms / 1e3) / 1e9 / chip["hbm_gbps"]
    return {
        "metric": f"fused_adam_step_ms_at_{n // 1_000_000}M_params_"
                  f"bf16p_f32state",
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": round(ref_ms / ms, 3),
        "hbm_frac": round(hbm_frac, 3),
        "efficiency_vs_ref": round(hbm_frac / 0.85, 3),
    }


def bench_layer_norm(jax, jnp, on_tpu, chip, floor_s):
    rows, cols = (8192, 4096) if on_tpu else (256, 512)
    from apex_tpu.normalization.fused_layer_norm import \
        fused_layer_norm_affine
    from apex_tpu.utils.benchtime import timed_steps

    x = jax.random.normal(jax.random.PRNGKey(0), (rows, cols), jnp.bfloat16)
    w = jnp.ones((cols,), jnp.float32)
    b = jnp.zeros((cols,), jnp.float32)
    iters = 50 if on_tpu else 2

    def fwd_step(i, x, w, b):
        # LN output is normalized, so chaining is numerically stable
        return fused_layer_norm_affine(x, w, b, cols).astype(x.dtype)

    ms_fwd = timed_steps(fwd_step, x, iters=iters, consts=(w, b),
                         floor_s=floor_s, donate=False)

    gradfn = jax.grad(
        lambda x, w, b: jnp.sum(fused_layer_norm_affine(x, w, b, cols)
                                .astype(jnp.float32) ** 2))

    def bwd_step(i, x, w, b):
        return (x + 1e-6 * gradfn(x, w, b).astype(x.dtype)).astype(x.dtype)

    ms_fb = timed_steps(bwd_step, x, iters=iters, consts=(w, b),
                        floor_s=floor_s, donate=False)

    n = rows * cols
    ref_fwd = (n * 4) / _A100_GBPS * 1e3  # r2 + w2 bytes
    hbm_frac = (n * 4) / (ms_fwd / 1e3) / 1e9 / chip["hbm_gbps"]
    return {
        "metric": f"layer_norm_fwd_ms_{rows}x{cols}_bf16",
        "value": round(ms_fwd, 3), "unit": "ms",
        "fwd_bwd_ms": round(ms_fb, 3),
        "vs_baseline": round(ref_fwd / ms_fwd, 3),
        "hbm_frac": round(hbm_frac, 3),
        "efficiency_vs_ref": round(hbm_frac / 0.85, 3),
    }


def bench_flash_attention(jax, jnp, on_tpu, chip, floor_s):
    b, h, s, d = (4, 16, 2048, 64) if on_tpu else (1, 2, 256, 64)
    from apex_tpu.ops.pallas.flash_attention import flash_attention
    from apex_tpu.utils.benchtime import timed_steps

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(k_, (b, h, s, d), jnp.bfloat16) * 0.2
               for k_ in ks)
    iters = 20 if on_tpu else 2

    def fwd_step(i, q, k, v):
        return flash_attention(q, k, v, True).astype(q.dtype)

    ms_fwd = timed_steps(fwd_step, q, iters=iters, consts=(k, v),
                         floor_s=floor_s, donate=False)

    gradfn = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, True).astype(jnp.float32) ** 2))

    def bwd_step(i, q, k, v):
        return (q + 1e-3 * gradfn(q, k, v).astype(q.dtype)).astype(q.dtype)

    ms_fb = timed_steps(bwd_step, q, iters=iters, consts=(k, v),
                        floor_s=floor_s, donate=False)

    # causal: 2 matmuls over s²/2 valid positions
    flops = 2 * 2 * b * h * s * s * d / 2
    tflops = flops / (ms_fwd / 1e3) / 1e12
    # A100 bf16 peak 312 TFLOPs; flash-attn fwd typically ~60% of peak
    ref_ms = flops / (312e12 * 0.6) * 1e3
    return {
        "metric": f"flash_attention_causal_fwd_ms_b{b}h{h}s{s}d{d}",
        "value": round(ms_fwd, 3), "unit": "ms",
        "fwd_bwd_ms": round(ms_fb, 3),
        "vs_baseline": round(ref_ms / ms_fwd, 3),
        "tflops": round(tflops, 1),
        "mxu_frac": round(tflops / chip["tflops"], 3),
    }


def bench_softmax_rope(jax, jnp, on_tpu, chip, floor_s):
    """Microbench for the megatron-kernel equivalents (VERDICT weak 7):
    scaled_upper_triang_masked_softmax and fused RoPE (sbhd). These are
    jnp+custom-VJP designs whose claim is that XLA fusion matches the
    reference's warp kernels — this measures that claim."""
    from apex_tpu.transformer.rope import fused_rope
    from apex_tpu.transformer.softmax import \
        scaled_upper_triang_masked_softmax
    from apex_tpu.utils.benchtime import timed_steps

    b, h, s, d = (8, 16, 1024, 64) if on_tpu else (1, 2, 128, 32)
    iters = 50 if on_tpu else 2
    x = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, s),
                          jnp.bfloat16) * 0.1

    def sm_step(i, x):
        # softmax output is a stable input distribution (rows sum to 1,
        # entries ~1/sk), so the carry chains straight through with NO
        # extra elementwise pass — the old `(y*s)*0.1` renorm was its own
        # read+write over the matrix and halved the apparent hbm_frac
        return scaled_upper_triang_masked_softmax(x, 0.5).astype(x.dtype)

    ms_sm = timed_steps(sm_step, x, iters=iters, floor_s=floor_s)
    sm_bytes = x.size * 2 * 2  # read + write bf16

    t = jax.random.normal(jax.random.PRNGKey(1), (s, b, h, d), jnp.bfloat16)

    freqs = (jnp.arange(s, dtype=jnp.float32)[:, None]
             * jnp.exp(-jnp.arange(d // 2, dtype=jnp.float32) / d))
    freqs = jnp.concatenate([freqs, freqs], axis=-1)  # (s, d)

    def rope_step(i, t, freqs):
        return fused_rope(t, freqs).astype(t.dtype)

    ms_rope = timed_steps(rope_step, t, iters=iters, consts=(freqs,),
                          floor_s=floor_s)
    rope_bytes = t.size * 2 * 2
    return {
        "metric": f"softmax_causal_fwd_ms_b{b}h{h}s{s}",
        "value": round(ms_sm, 3), "unit": "ms",
        "hbm_frac": round(sm_bytes / (ms_sm / 1e3) / 1e9
                          / chip["hbm_gbps"], 3),
        "rope_sbhd_ms": round(ms_rope, 3),
        "rope_hbm_frac": round(rope_bytes / (ms_rope / 1e3) / 1e9
                               / chip["hbm_gbps"], 3),
        "vs_baseline": round(((sm_bytes / _A100_GBPS * 1e3) / ms_sm), 3),
    }


def bench_resnet50(jax, jnp, on_tpu, chip, floor_s):
    from apex_tpu.models.resnet import ResNet18ish, ResNet50
    from apex_tpu.optimizers.functional import adam_update
    from apex_tpu.utils.benchtime import timed_steps

    if on_tpu:
        model, batch, hw = ResNet50(), 128, 224
    else:
        model, batch, hw = ResNet18ish(), 8, 32
    x = jax.random.normal(jax.random.PRNGKey(0), (batch, hw, hw, 3),
                          jnp.bfloat16)
    y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0,
                           1000 if on_tpu else 10, jnp.int32)
    variables = model.init(jax.random.PRNGKey(2), x)
    params, bstats = variables["params"], variables["batch_stats"]
    m0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                                params)
    v0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                                params)

    def train_step(i, state, x, y):
        params, m, v, bstats = state

        def loss_fn(p):
            logits, updated = model.apply(
                {"params": p, "batch_stats": bstats}, x,
                mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, logits.shape[-1])
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * onehot, axis=-1))
            return loss, updated["batch_stats"]

        (loss, bs2), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        params, m, v = adam_update(params, grads, m, v, step=i + 1,
                                   lr=1e-3, weight_decay=1e-4)
        return (params, m, v, bs2)

    iters = 10 if on_tpu else 2
    ms = timed_steps(train_step, (params, m0, v0, bstats), iters=iters,
                     consts=(x, y), floor_s=floor_s)
    imgs_sec = batch / (ms / 1e3)
    # MLPerf-class A100 ResNet-50 ≈ 2900 imgs/sec/GPU (amp, DALI input)
    ref = 2900.0 if on_tpu else float("nan")
    entry = {
        "metric": f"resnet50_train_imgs_per_sec_b{batch}_{hw}px"
                  if on_tpu else
                  f"resnet18ish_train_imgs_per_sec_b{batch}_{hw}px",
        "value": round(imgs_sec, 1), "unit": "imgs/sec",
        "step_ms": round(ms, 2),
    }
    if on_tpu:
        entry["vs_baseline"] = round(imgs_sec / ref, 3)
    else:
        entry["vs_baseline"] = 0.0
    return entry


def bench_bert_lamb(jax, jnp, on_tpu, chip, floor_s):
    """BASELINE config 4 (single-chip slice): BERT-large MLM-style train step
    with fused LAMB — exercises FusedRMSNorm-class fused LN, xentropy-style
    loss, and the two-phase LAMB trust-ratio update
    (csrc/multi_tensor_lamb.cu via optimizers/functional.lamb_update)."""
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models.bert import Bert, BertConfig
    from apex_tpu.optimizers.functional import lamb_update
    from apex_tpu.utils.benchtime import timed_steps

    if on_tpu:
        # b32 keeps every matmul MXU-shaped (b8 left the 1024-wide GEMMs
        # M-starved at s128); metric name records the config
        cfg, batch, seq = BertConfig.large(), 32, 128
    else:
        cfg, batch, seq = BertConfig.tiny(), 2, 32
    model = Bert(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                cfg.vocab_size, jnp.int32)
    labels = jnp.roll(tokens, 1, axis=1)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    m0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                                params)
    v0 = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32),
                                params)
    nparams = sum(p.size for p in jax.tree_util.tree_leaves(params))

    def train_step(i, state, tokens, labels):
        params, m, v = state

        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            # the BASELINE config-4 loss: contrib.xentropy (gather-based
            # fused CE, one lse residual) — not an O(N·V) onehot matmul
            return jnp.mean(softmax_cross_entropy_loss(
                logits.astype(jnp.float32), labels))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, m, v, _gnorm = lamb_update(params, grads, m, v, step=i + 1,
                                           lr=1e-3, weight_decay=0.01)
        return (params, m, v)

    iters = 10 if on_tpu else 2
    ms = timed_steps(train_step, (params, m0, v0), iters=iters,
                     consts=(tokens, labels), floor_s=floor_s)
    seqs_sec = batch / (ms / 1e3)
    # model-FLOPs baseline: train ≈ 6·params·tokens per seq; apex+LAMB BERT
    # on A100 sustains ~45% MFU of 312 bf16 TFLOPs (MLPerf-class recipe) —
    # vs_baseline is our throughput over that A100 estimate, mfu is the
    # chip-fair absolute
    step_flops = 6.0 * nparams * batch * seq
    mfu = step_flops / (ms / 1e3) / 1e12 / chip["tflops"]
    a100_seqs = (312e12 * 0.45) / (6.0 * nparams * seq)
    return {
        "metric": f"bert_{'large' if on_tpu else 'tiny'}_lamb_train_"
                  f"seqs_per_sec_b{batch}_s{seq}",
        "value": round(seqs_sec, 2), "unit": "seqs/sec",
        "step_ms": round(ms, 2), "params_m": round(nparams / 1e6, 1),
        "mfu": round(mfu, 3),
        "vs_baseline": round(seqs_sec / a100_seqs, 3),
    }


def bench_gpt2_fwd(jax, jnp, on_tpu, chip, floor_s):
    """BASELINE config 5 (single-chip slice): GPT-2 1.5B (xl) bf16 forward —
    the megatron softmax + RoPE + flash MHA stack at full model scale (the
    1.5B TRAIN step is a multi-chip job; fwd at 3 GB of bf16 params is the
    single-chip capability claim)."""
    from apex_tpu.models.gpt2 import GPT2, GPT2Config
    from apex_tpu.utils.benchtime import timed_steps

    if on_tpu:
        cfg, batch = GPT2Config.xl(), 4
    else:
        cfg, batch = GPT2Config.tiny(), 1
    cfg = type(cfg)(**{**cfg.__dict__, "n_positions": 512}) if on_tpu else cfg
    seq = min(cfg.n_positions, 512)
    model = GPT2(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                cfg.vocab_size, jnp.int32)
    params = model.init(jax.random.PRNGKey(1), tokens)
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16)
        if p.dtype == jnp.float32 else p, params)
    nparams = sum(p.size for p in jax.tree_util.tree_leaves(params))

    def fwd_step(i, carry, params, tokens):
        # derive the inputs from the carry and fold the FULL logits back in:
        # an invariant body gets hoisted out of the while loop, and summing a
        # logits slice lets XLA narrow the lm-head matmul to that slice —
        # either way the "measurement" would stop measuring the forward.
        # (1e-30 scale, not *0: a zero multiply is itself simplifiable)
        toks = (tokens + carry.astype(jnp.int32) % cfg.vocab_size) \
            % cfg.vocab_size
        logits = model.apply(params, toks)
        return carry * 0.5 + jnp.sum(logits.astype(jnp.float32)) * 1e-30

    iters = 10 if on_tpu else 2
    ms = timed_steps(fwd_step, jnp.float32(0.0), iters=iters,
                     consts=(params, tokens), floor_s=floor_s,
                     donate=False)
    toks_sec = batch * seq / (ms / 1e3)
    # model-FLOPs baseline: fwd ≈ 2·params per token; a well-tuned A100
    # inference fwd sustains ~55% MFU of 312 bf16 TFLOPs
    mfu = 2.0 * nparams * toks_sec / 1e12 / chip["tflops"]
    a100_toks = (312e12 * 0.55) / (2.0 * nparams)
    return {
        "metric": f"gpt2_{'xl_1p5b' if on_tpu else 'tiny'}_fwd_"
                  f"tokens_per_sec_b{batch}_s{seq}",
        "value": round(toks_sec, 1), "unit": "tokens/sec",
        "step_ms": round(ms, 2), "params_m": round(nparams / 1e6, 1),
        "mfu": round(mfu, 3),
        "vs_baseline": round(toks_sec / a100_toks, 3),
    }


BENCHES = [("fused_adam_1b", bench_fused_adam),
           ("layer_norm", bench_layer_norm),
           ("flash_attention", bench_flash_attention),
           ("softmax_rope", bench_softmax_rope),
           ("resnet50_train", bench_resnet50),
           ("bert_lamb", bench_bert_lamb),
           ("gpt2_fwd", bench_gpt2_fwd)]

_HERE = os.path.dirname(os.path.abspath(__file__))
_CACHE = os.path.join(_HERE, "BENCH_TPU_CACHE.json")


def atomic_write_json(path: str, obj) -> None:
    """Write-tmp-then-rename so concurrent readers (bench.py polls the
    worker's incremental artifacts) never observe a truncated file. Shared
    by bench, chipcheck, the chip worker and its queue jobs."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)


def run_suite(jax, jnp, backend: str, out_path: str | None = None,
              only=None) -> dict:
    """Run every bench against an ALREADY-initialized backend. The suite
    dict is rewritten to ``out_path`` after each bench so a mid-run crash
    (or relay death) still leaves a partial artifact on disk. Callable from
    the background chip worker (tools/chip_worker.py) without re-probing.

    ``only``: optional collection of bench names (``apex-tpu-bench
    --kernels``) restricting the run to that subset; unknown names raise
    so a typo cannot silently produce an empty baseline."""
    if only is not None:
        known = {name for name, _ in BENCHES}
        unknown = sorted(set(only) - known)
        if unknown:
            raise ValueError(f"unknown bench name(s) {unknown}; "
                             f"known: {sorted(known)}")
    from apex_tpu.utils.benchtime import measure_fetch_floor

    on_tpu = backend == "tpu"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    chip = _CHIP.get(gen, _CHIP["v5e"])
    floor_s = measure_fetch_floor()

    # capture provenance — device_kind/interpret_mode/git/captured from
    # THE shared builder (apex-tpu-bench --serve stamps identically), so
    # check_regression compares consistently stamped captures; its
    # interpret_mode honors APEX_TPU_FORCE_COMPILED, which `not on_tpu`
    # would misreport
    from apex_tpu.utils.env import capture_provenance

    suite = {"backend": backend, "chip": gen if on_tpu else "cpu-smoke",
             **capture_provenance(),
             "fetch_floor_ms": round(floor_s * 1e3, 1),
             "complete": False}

    def flush():
        if out_path is not None:
            atomic_write_json(out_path, suite)

    flush()
    for name, fn in BENCHES:
        if only is not None and name not in only:
            continue
        try:
            t0 = time.perf_counter()
            entry = fn(jax, jnp, on_tpu, chip, floor_s)
            entry["bench_wall_s"] = round(time.perf_counter() - t0, 1)
            suite[name] = entry
            print(f"[bench] {name}: {entry}", file=sys.stderr, flush=True)
        except Exception as e:  # a failing sub-bench must not kill the suite
            suite[name] = {"error": f"{type(e).__name__}: {e}"}
            print(f"[bench] {name} FAILED: {e}", file=sys.stderr, flush=True)
        flush()
    # a subset capture must never read as a full suite (bench.py's cache
    # promotion and the regression gate both key off "complete")
    suite["complete"] = only is None
    if only is not None:
        suite["subset"] = sorted(only)
    flush()
    return suite


def _emit(suite, cached: bool) -> None:
    """Print the one-line headline record and exit accordingly."""
    backend = suite.get("backend", "unknown")
    headline = suite.get("fused_adam_1b")
    if not isinstance(headline, dict) or "error" in headline:
        headline = {"metric": "fused_adam_step_ms", "value": -1.0,
                    "unit": "ms", "vs_baseline": 0.0}
    line = {k: headline[k] for k in
            ("metric", "value", "unit", "vs_baseline")}
    # the backend is part of the record: a CPU-smoke capture must be
    # unmistakable AND fail the run (rounds 1-2 shipped silent cpu rc=0)
    line["backend"] = backend
    if not suite.get("complete"):
        # a partial capture (e.g. headline-only q005) must be unmistakable
        # in the one-line record, not just in the untracked cache file
        line["complete"] = False
    if cached:
        line["cached"] = True
        line["captured"] = suite.get("captured")
    if backend != "tpu":
        # a relay-down round still proves the compile path: surface the
        # deviceless AOT artifacts (Mosaic kernel zoo, headline models,
        # distributed stack — all compiled for v5e with no chip) in the
        # one-line record the driver keeps
        ev = {}
        for key, fname in (("kernels", "MOSAIC_AOT.json"),
                           ("models", "MODEL_AOT.json"),
                           ("stack", "STACK_AOT.json")):
            try:
                with open(os.path.join(_HERE, fname)) as f:
                    ev[key] = bool(json.load(f).get("ok"))
            except Exception:
                ev[key] = False
        line["aot_compiled_v5e"] = ev
    print(json.dumps(line))
    if backend != "tpu":
        print("[bench] FAILED to reach the TPU — this is a CPU smoke "
              "record, not an acceptance artifact", file=sys.stderr)
        sys.exit(3)
    sys.exit(0)


def _load_cache(require_complete: bool = True, max_age_h: float = 14.0):
    """Return the TPU capture if it is usable, else None. ``max_age_h``
    rejects captures from a previous round (the driver restarts rounds on a
    ~12 h cadence, so 14 h covers this round's earliest capture while
    shutting out last round's committed one; the 'captured' stamp and
    'git' rev are also carried into the emitted headline so the record is
    auditable)."""
    try:
        with open(_CACHE) as f:
            suite = json.load(f)
    except Exception:
        return None
    if suite.get("backend") != "tpu":
        return None
    if require_complete and not suite.get("complete"):
        return None
    if not isinstance(suite.get("fused_adam_1b"), dict) or \
            "error" in suite["fused_adam_1b"]:
        return None
    try:
        age_s = time.time() - time.mktime(
            time.strptime(suite["captured"], "%Y-%m-%dT%H:%M:%S"))
        if age_s > max_age_h * 3600:
            return None
    except Exception:
        return None
    return suite


def _worker_alive() -> bool:
    """Is the background chip worker (tools/chip_worker.py) holding the
    chip right now? If so, probing the relay from here would fail (and
    SIGTERM-ing a hung probe risks wedging it) — prefer waiting for the
    worker's incremental cache instead."""
    path = os.path.join(_HERE, "tools", "chipq", "status.json")
    try:
        with open(path) as f:
            st = json.load(f)
        if st.get("phase") == "exited":
            return False
        if time.time() - os.path.getmtime(path) > 4 * 3600:
            return False  # stale status (committed snapshot + pid reuse)
        pid = int(st["pid"])
        os.kill(pid, 0)
        # pid liveness is not identity: verify it IS the worker (a fresh
        # checkout's status.json + pid collision must not stall bench.py)
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmd = f.read().decode("utf-8", "replace")
        return "chip_worker" in cmd
    except Exception:
        return False


def main():
    """Fast, wedge-proof reporter. Preference order:

    1. A TPU-backed ``BENCH_TPU_CACHE.json`` written by the background chip
       worker this round — emit in milliseconds, no backend init at all.
    2. Worker alive but cache not ready: poll for the cache (<=10 min).
    3. No worker: bounded relay patience (6 min), then a LIVE suite run.
    4. CPU smoke fallback — loud, rc=3, but always a parseable line.

    rc=124 (driver window timeout, the round-3 artifact killer) is designed
    out: every path above is bounded well under the driver's window."""
    suite = _load_cache()
    if suite is not None:
        atomic_write_json(os.path.join(_HERE, "BENCH_SUITE.json"), suite)
        _emit(suite, cached=True)

    # on the CPU-smoke re-exec, skip the worker poll (it already failed
    # once — re-entering it would loop forever)
    worker_was_alive = (os.environ.get("APEX_TPU_BENCH_CPU") != "1"
                        and _worker_alive())
    if worker_was_alive:
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            suite = _load_cache()
            if suite is not None:
                break
            if not _worker_alive():  # died/idle-exited: stop burning the
                break                # driver window waiting on nothing
            time.sleep(20)
        suite = _load_cache()
        if suite is not None:  # complete capture: promote to the suite file
            atomic_write_json(os.path.join(_HERE, "BENCH_SUITE.json"),
                              suite)
            _emit(suite, cached=True)
        partial = _load_cache(require_complete=False)
        if partial is not None:
            # partial capture at deadline: emit it (a real-TPU headline beats
            # a CPU smoke) but do NOT overwrite the tracked BENCH_SUITE.json
            # — that file's contract is "best-known COMPLETE real-TPU
            # capture" and a committed full suite must survive a
            # headline-only q005 run (ADVICE r4)
            _emit(partial, cached=True)

    if worker_was_alive and _worker_alive():
        # the worker still holds the chip and never produced a usable
        # capture: probing the relay against it would fail (or worse, a
        # SIGTERM-ed hung probe could wedge it) and run_suite would race
        # the worker's writer — go straight to the loud CPU smoke.
        from __graft_entry__ import sanitized_cpu_env
        env = sanitized_cpu_env()
        env["APEX_TPU_BENCH_CPU"] = "1"
        os.execve(sys.executable, [sys.executable, __file__], env)

    jax, backend = _backend_with_timeout(probe_s=120, total_s=360)
    import jax.numpy as jnp

    out = os.path.join(
        _HERE, "BENCH_TPU_CACHE.json" if backend == "tpu"
        else "BENCH_SMOKE.json")
    suite = run_suite(jax, jnp, backend, out_path=out)
    if backend == "tpu":
        atomic_write_json(os.path.join(_HERE, "BENCH_SUITE.json"), suite)
    _emit(suite, cached=False)


if __name__ == "__main__":
    main()
