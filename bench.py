"""Benchmark driver — prints ONE JSON line.

Headline metric (BASELINE.json row 1): fused Adam step latency at 1B params on
one TPU chip, via the flat-buffer Pallas kernel
(apex_tpu/ops/pallas/fused_adam_kernel.py) — the TPU equivalent of the
reference's ``multi_tensor_adam`` launch path (csrc/multi_tensor_adam.cu:24 via
csrc/multi_tensor_apply.cuh:32-103).

Dtype mix matches the reference's common mixed-precision setup: bf16 params +
bf16 grads + fp32 exp_avg/exp_avg_sq (fused_adam.py:212-232 groups). The op is
HBM-bandwidth bound: bytes = N·(2+2+4+4) read + N·(2+4+4) written = 22N.

``vs_baseline``: measured A100-class reference estimate for the same op =
22N bytes / (1555 GB/s · 0.85 achievable) — apex's multi_tensor kernels reach
~85% of HBM peak on large flat lists. vs_baseline = ref_ms / our_ms
(>1 ⇒ faster than the A100 reference path).

On non-TPU hosts (CI smoke) a small N keeps runtime sane; the driver runs this
on the real chip.
"""

from __future__ import annotations

import json
import os

import subprocess
import sys
import time


def _backend_with_timeout(seconds: int = 180):
    """Initialize the JAX backend, guarding against a wedged TPU relay (the
    axon sitecustomize initializes the TPU client on ANY backend request and
    can hang indefinitely if a previous holder died mid-claim; the hang sits
    in C so in-process alarms can't interrupt it). Probe in a subprocess with
    a hard timeout; if the probe hangs, re-exec this script on pure CPU
    (axon hook stripped) so the driver still gets a JSON line."""
    if os.environ.get("APEX_TPU_BENCH_CPU") != "1":
        # SIGTERM (not SIGKILL) on timeout so the probe can release its TPU
        # claim cleanly — a hard kill mid-claim would itself wedge the relay
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        try:
            ok = proc.wait(timeout=seconds) == 0
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
            ok = False
        if not ok:
            env = dict(os.environ)
            env["APEX_TPU_BENCH_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            # strip only the axon site hook; keep the caller's other entries
            here = os.path.dirname(os.path.abspath(__file__))
            kept = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                    if p and "axon" not in p]
            env["PYTHONPATH"] = os.pathsep.join(kept + [here])
            os.execve(sys.executable, [sys.executable, __file__], env)

    import jax

    return jax, jax.default_backend()


def main():
    jax, backend = _backend_with_timeout()
    import jax.numpy as jnp

    on_tpu = backend == "tpu"
    n = 1_000_000_000 if on_tpu else 1_048_576  # CPU smoke runs interpret mode
    # round to the flat-buffer tile granularity (8*128)
    n = (n // 1024) * 1024

    from apex_tpu.ops.pallas.fused_adam_kernel import fused_adam_flat

    key = jax.random.PRNGKey(0)
    p = jax.random.normal(key, (n,), jnp.bfloat16) * 0.02
    g = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.bfloat16)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)

    def step(p, g, m, v, s):
        return fused_adam_flat(p, g, m, v, lr=1e-3, weight_decay=0.01,
                               step=s, inv_scale=1.0)

    # warmup / compile
    p, m, v = step(p, g, m, v, jnp.int32(1))
    p.block_until_ready()

    iters = 20 if on_tpu else 2
    t0 = time.perf_counter()
    for i in range(iters):
        p, m, v = step(p, g, m, v, jnp.int32(2 + i))
    p.block_until_ready()
    ms = (time.perf_counter() - t0) / iters * 1e3

    bytes_moved = n * (2 + 2 + 4 + 4 + 2 + 4 + 4)
    ref_ms = bytes_moved / (1555e9 * 0.85) * 1e3  # A100 apex estimate
    print(json.dumps({
        "metric": f"fused_adam_step_ms_at_{n//1_000_000}M_params_"
                  f"bf16p_f32state",
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": round(ref_ms / ms, 3),
    }))


if __name__ == "__main__":
    main()
