"""Queue job (runs LAST): commit every on-chip artifact the earlier jobs
produced, so a relay that returns after the interactive session ends
still leaves the silicon evidence in git history rather than only in the
working tree. Artifact-only: never touches source (the self-applying
jobs q080/q085 own their gated source commits)."""

import json
import os
import subprocess
import sys
import time

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402

if jax.default_backend() != "tpu" and \
        os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError("backend is not tpu")

ARTIFACTS = [
    "BENCH_SUITE.json", "CHIPCHECK.json",
    "RESNET50_ROOFLINE.json", "L1_AMP_SLICE.json",
    "FLASH_DEFAULTS_APPLIED.json", "ADAM_BLOCK_APPLIED.json",
    "tools/tune_flash.out", "tools/tune_adam.out",
    "tools/tune_softmax.out",
]

# promote the (gitignored) incremental cache to the tracked suite file
# under the same rules bench.py uses: complete, TPU-backed
try:
    with open(os.path.join(ROOT, "BENCH_TPU_CACHE.json")) as f:
        cache = json.load(f)
    if cache.get("backend") == "tpu" and cache.get("complete"):
        import bench

        bench.atomic_write_json(os.path.join(ROOT, "BENCH_SUITE.json"),
                                cache)
except Exception:
    pass

present = [a for a in ARTIFACTS if os.path.exists(os.path.join(ROOT, a))]
if not present:
    raise AssertionError("no artifacts to commit yet")
subprocess.run(["git", "add", "--"] + present, cwd=ROOT, check=True)
# restrict BOTH the staged listing and the commit to the artifact
# pathspec: anything else sitting in the shared index (e.g. a q080 source
# patch whose gated commit failed midway) must never ride along
diff = subprocess.run(["git", "diff", "--cached", "--name-only", "--"]
                      + present,
                      cwd=ROOT, capture_output=True, text=True, check=True)
staged = [ln for ln in diff.stdout.splitlines() if ln.strip()]
if staged:
    # summarize the headline for the commit message if available
    head = ""
    try:
        with open(os.path.join(ROOT, "BENCH_TPU_CACHE.json")) as f:
            s = json.load(f)
        adam = s.get("fused_adam_1b", {})
        head = (f" (backend={s.get('backend')}, fused_adam "
                f"{adam.get('value')} {adam.get('unit')})")
    except Exception:
        pass
    subprocess.run(
        ["git", "commit", "-q", "-m",
         f"On-chip artifacts from the background queue{head}",
         "-m", "Files: " + ", ".join(staged), "--"] + staged,
        cwd=ROOT, check=True)
print(json.dumps({"committed": staged,
                  "t": time.strftime("%Y-%m-%dT%H:%M:%S")}))
