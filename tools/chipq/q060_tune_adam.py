"""Chip job: fused-Adam flat-kernel block sweep at the 1B headline shape.

The headline metric sits at 0.80 HBM frac with 512-row blocks; this sweeps
the streaming block size to find the bandwidth knee. One JSON line per
config appended to tools/tune_adam.out.
"""

import json
import os
import sys
import time

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if jax.default_backend() != "tpu" and \
        os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError("backend is not tpu")

from apex_tpu.ops.pallas.fused_adam_kernel import (LANE,  # noqa: E402
                                                   fused_adam_flat)
from apex_tpu.utils.benchtime import (measure_fetch_floor,  # noqa: E402
                                      timed_steps)

ON_TPU = jax.default_backend() == "tpu"
gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
peak_gbps = {"v5e": 819.0, "v6e": 1640.0, "v5p": 2765.0}.get(gen, 819.0)
n = 999_999_488 if ON_TPU else 1_048_576
rows = n // LANE
floor_s = measure_fetch_floor()

# CPU (smoke) runs must never pollute the real sweep file q085 reads
out_path = os.path.join(ROOT, "tools",
                        "tune_adam.out" if ON_TPU
                        else "tune_adam_smoke.out")
best = None
with open(out_path, "a") as out:
    print(f"# backend={jax.default_backend()} n={n}", file=out, flush=True)
    for br in ([256, 512, 1024, 2048, 4096] if ON_TPU else [512]):
        p = jax.random.normal(jax.random.PRNGKey(0), (rows, LANE),
                              jnp.bfloat16) * 0.02
        g = jax.random.normal(jax.random.PRNGKey(1), (rows, LANE),
                              jnp.bfloat16)
        m = jnp.zeros((rows, LANE), jnp.float32)
        v = jnp.zeros((rows, LANE), jnp.float32)

        def step(i, st, g, br=br):
            p, m, v = st
            return fused_adam_flat(p, g, m, v, lr=1e-3, weight_decay=0.01,
                                   step=i + 1, inv_scale=1.0,
                                   block_rows=br)

        try:
            t0 = time.time()
            ms = timed_steps(step, (p, m, v), iters=30 if ON_TPU else 2,
                             consts=(g,), floor_s=floor_s)
            frac = n * 22 / (ms / 1e3) / 1e9 / peak_gbps
            rec = {"block_rows": br, "ms": round(ms, 3),
                   "hbm_frac": round(frac, 3),
                   "wall_s": round(time.time() - t0, 1)}
            print(json.dumps(rec), file=out, flush=True)
            if best is None or rec["hbm_frac"] > best["hbm_frac"]:
                best = rec
        except Exception as e:
            print(json.dumps({"block_rows": br,
                              "error": f"{type(e).__name__}: {e}"}),
                  file=out, flush=True)
        finally:
            del p, g, m, v
    print(json.dumps({"best": best,
                      "backend": jax.default_backend()}),
          file=out, flush=True)
if best is None:
    raise AssertionError("no successful config")
