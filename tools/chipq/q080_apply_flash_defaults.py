"""Chip job: set flash-attention block defaults from the q030 sweep.

Reads the best (bq, bk) from tools/tune_flash.out, patches
DEFAULT_BLOCK_Q/K in the kernel source, COMMITS the change, then
re-measures through the public frontend (worker purges modules between
jobs, so the fresh import picks up the edit) and records the
verification in FLASH_DEFAULTS_APPLIED.json. Runs after q030 by queue
order; fails (and retries later) if the sweep output is absent.
"""

import json
import os
import re
import sys
import time

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)
_QDIR = os.path.dirname(os.path.abspath(__file__))
if _QDIR not in sys.path:  # for the _gate commit-gate helper
    sys.path.insert(0, _QDIR)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if jax.default_backend() != "tpu" and \
        os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError("backend is not tpu")

sweep_path = os.path.join(ROOT, "tools", "tune_flash.out")
best = None
with open(sweep_path) as f:
    for line in f:
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec.get("best"), dict):
                # apply only TPU-measured bests; records without a
                # backend stamp predate it and are known-TPU (the smoke
                # path writes tune_flash_smoke.out since round 5)
                if rec.get("backend", "tpu") == "tpu":
                    best = rec["best"]
if best is None or "bq" not in best:
    raise AssertionError("no TPU best config in tune_flash.out yet")
bq, bk = int(best["bq"]), int(best["bk"])

kpath = os.path.join(ROOT, "apex_tpu", "ops", "pallas",
                     "flash_attention.py")
src = open(kpath).read()
cur_q = int(re.search(r"DEFAULT_BLOCK_Q = (\d+)", src).group(1))
cur_k = int(re.search(r"DEFAULT_BLOCK_K = (\d+)", src).group(1))
changed = (cur_q, cur_k) != (bq, bk)
gate = None
# source is only ever patched from an on-chip run: an allowed-CPU dry-run
# stops at the parse (the apply jobs have no legitimate CPU mode)
if changed and jax.default_backend() != "tpu":
    changed = False
if changed:
    src = re.sub(r"DEFAULT_BLOCK_Q = \d+", f"DEFAULT_BLOCK_Q = {bq}", src)
    src = re.sub(r"DEFAULT_BLOCK_K = \d+", f"DEFAULT_BLOCK_K = {bk}", src)
    open(kpath, "w").write(src)
    # commit gate (VERDICT r4 item 8): the fast parity subset must pass
    # on the patched source before the autonomous commit (revert on
    # failure, raise on timeout so the worker's backoff retries)
    from _gate import gated_commit

    res = gated_commit(
        kpath,
        f"Set flash block defaults from on-chip sweep: bq={bq} bk={bk} "
        f"(was {cur_q}/{cur_k}; fwd {best.get('fwd_tflops')} TFLOPs, "
        f"mxu {best.get('fwd_mxu')}; parity gate passed)")
    gate = res["gate"]
    changed = res["applied"]

# verify: re-measure through the frontend at the (possibly new) defaults
import importlib  # noqa: E402

for m in [m for m in sys.modules if m.startswith("apex_tpu")]:
    del sys.modules[m]
from apex_tpu.ops.pallas import flash_attention as fa  # noqa: E402
from apex_tpu.utils.benchtime import (measure_fetch_floor,  # noqa: E402
                                      timed_steps)

ON_TPU = jax.default_backend() == "tpu"
b, h, s, d = (4, 16, 2048, 64) if ON_TPU else (1, 2, 256, 64)
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q, k, v = (jax.random.normal(k_, (b, h, s, d), jnp.bfloat16) * 0.2
           for k_ in ks)
ms = timed_steps(
    lambda i, q, k, v: fa.flash_attention(q, k, v, True).astype(q.dtype),
    q, iters=20 if ON_TPU else 2, consts=(k, v),
    floor_s=measure_fetch_floor(), donate=False)
fl = 2 * 2 * b * h * s * s * d / 2
rec = {"applied": {"bq": fa.DEFAULT_BLOCK_Q, "bk": fa.DEFAULT_BLOCK_K},
       "was": {"bq": cur_q, "bk": cur_k}, "changed": changed,
       "test_gate": gate,
       "sweep_best": best, "verify_fwd_ms": round(ms, 3),
       "verify_fwd_tflops": round(fl / (ms / 1e3) / 1e12, 1),
       "captured": time.strftime("%Y-%m-%dT%H:%M:%S")}
import bench  # noqa: E402

bench.atomic_write_json(os.path.join(ROOT, "FLASH_DEFAULTS_APPLIED.json"),
                        rec)
print(json.dumps(rec))
