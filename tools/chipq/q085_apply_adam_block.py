"""Chip job: set the fused-Adam streaming block from the q060 sweep.

Reads the best block_rows from tools/tune_adam.out, patches
DEFAULT_BLOCK_ROWS in the kernel, commits, and records the application.
Only commits when the winner beats the current default's measured frac by
>2% (block choice is a plateau; don't churn the source for noise).
"""

import json
import os
import re
import sys
import time

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)
_QDIR = os.path.dirname(os.path.abspath(__file__))
if _QDIR not in sys.path:  # for the _gate commit-gate helper
    sys.path.insert(0, _QDIR)

import jax  # noqa: E402

if jax.default_backend() != "tpu" and \
        os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError("backend is not tpu")

best = None
rows = {}
with open(os.path.join(ROOT, "tools", "tune_adam.out")) as f:
    for line in f:
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec.get("best"), dict):
                # apply only TPU-measured bests (smoke runs write to
                # tune_adam_smoke.out since round 5; unstamped records
                # predate the stamp and are known-TPU)
                if rec.get("backend", "tpu") == "tpu":
                    best = rec["best"]
            elif "block_rows" in rec and "hbm_frac" in rec:
                rows[rec["block_rows"]] = rec["hbm_frac"]
if best is None:
    raise AssertionError("no TPU best config in tune_adam.out yet")

kpath = os.path.join(ROOT, "apex_tpu", "ops", "pallas",
                     "fused_adam_kernel.py")
src = open(kpath).read()
cur = int(re.search(r"DEFAULT_BLOCK_ROWS = (\d+)", src).group(1))
cur_frac = rows.get(cur)
# incumbent row missing/errored ⇒ there is no comparison to justify a
# source change; skip instead of letting cur_frac=0.0 pass the no-churn
# gate trivially (ADVICE r4)
apply = (cur_frac is not None
         and int(best["block_rows"]) != cur
         and best["hbm_frac"] > cur_frac * 1.02)
gate = None
# source is only ever patched from an on-chip run: an allowed-CPU dry-run
# stops at the parse (the apply jobs have no legitimate CPU mode)
if apply and jax.default_backend() != "tpu":
    apply = False
if apply:
    src = re.sub(r"DEFAULT_BLOCK_ROWS = \d+",
                 f"DEFAULT_BLOCK_ROWS = {int(best['block_rows'])}", src)
    open(kpath, "w").write(src)
    # commit gate (VERDICT r4 item 8): parity subset must pass on the
    # patched source (revert on failure, raise on timeout so the
    # worker's backoff retries)
    from _gate import gated_commit

    res = gated_commit(
        kpath,
        f"Set fused-Adam streaming block from on-chip sweep: "
        f"{best['block_rows']} rows ({best['hbm_frac']} HBM frac vs "
        f"{cur_frac} at {cur}; parity gate passed)")
    gate = res["gate"]
    apply = res["applied"]

import bench  # noqa: E402

bench.atomic_write_json(
    os.path.join(ROOT, "ADAM_BLOCK_APPLIED.json"),
    {"applied": apply, "best": best, "previous": cur,
     "previous_frac": cur_frac, "test_gate": gate,
     "captured": time.strftime("%Y-%m-%dT%H:%M:%S")})
print(json.dumps({"applied": apply, "best": best}))
