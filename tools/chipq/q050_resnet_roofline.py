"""Chip job: ResNet-50 step roofline + trace (VERDICT r3 item 3).

Builds the exact bench train step, compiles it, pulls XLA's own
cost_analysis (flops + bytes accessed) and compares the measured step time
against the chip roofline max(flops/peak, bytes/bw) — proving whether the
0.80x-A100 residual is chip-bound or implementation slack. Also attempts a
jax.profiler device trace (best-effort on the tunneled runtime). Writes
RESNET50_ROOFLINE.json.
"""

import json
import os
import sys
import time

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bench  # noqa: E402
from apex_tpu.models.resnet import ResNet18ish, ResNet50  # noqa: E402
from apex_tpu.optimizers.functional import adam_update  # noqa: E402
from apex_tpu.utils.benchtime import (measure_fetch_floor,  # noqa: E402
                                      timed_steps)

backend = jax.default_backend()
ON_TPU = backend == "tpu"
gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
chip = bench._CHIP.get(gen, bench._CHIP["v5e"])

if ON_TPU:
    model, batch, hw, ncls = ResNet50(), 128, 224, 1000
else:
    model, batch, hw, ncls = ResNet18ish(), 8, 32, 10

x = jax.random.normal(jax.random.PRNGKey(0), (batch, hw, hw, 3),
                      jnp.bfloat16)
y = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, ncls, jnp.int32)
variables = model.init(jax.random.PRNGKey(2), x)
params, bstats = variables["params"], variables["batch_stats"]
zeros = lambda p: jnp.zeros_like(p, jnp.float32)  # noqa: E731
m0 = jax.tree_util.tree_map(zeros, params)
v0 = jax.tree_util.tree_map(zeros, params)


def train_step(i, state, x, y):
    params, m, v, bstats = state

    def loss_fn(p):
        logits, updated = model.apply(
            {"params": p, "batch_stats": bstats}, x, mutable=["batch_stats"])
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        loss = -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits) * onehot, axis=-1))
        return loss, updated["batch_stats"]

    (loss, bs2), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    params, m, v = adam_update(params, grads, m, v, step=i + 1,
                               lr=1e-3, weight_decay=1e-4)
    return (params, m, v, bs2)


# --- XLA's own cost model for ONE step -----------------------------------
one = jax.jit(lambda st, x, y: train_step(0, st, x, y))
compiled = one.lower((params, m0, v0, bstats), x, y).compile()
ca = compiled.cost_analysis()
ca = ca[0] if isinstance(ca, (list, tuple)) else ca
flops = float(ca.get("flops", 0.0))
bytes_acc = float(ca.get("bytes accessed", 0.0))

result = {"backend": backend, "chip": gen if ON_TPU else "cpu",
          "batch": batch, "px": hw,
          "captured": time.strftime("%Y-%m-%dT%H:%M:%S"),
          "xla_flops_per_step": flops,
          "xla_bytes_per_step": bytes_acc}

# --- measured step time --------------------------------------------------
floor_s = measure_fetch_floor()
iters = 10 if ON_TPU else 2
# donate=False: the profiler-trace block below re-executes the step on
# this same state tuple; donation would leave it deleted (ADVICE r4) and
# ResNet-50 state (~300 MB fp32) comfortably fits HBM without aliasing
ms = timed_steps(train_step, (params, m0, v0, bstats), iters=iters,
                 consts=(x, y), floor_s=floor_s, donate=False)
result["measured_step_ms"] = round(ms, 2)
result["imgs_per_sec"] = round(batch / (ms / 1e3), 1)

from apex_tpu.utils.prof import roofline  # noqa: E402

rl = roofline(lambda st, x, y: train_step(0, st, x, y),
              (params, m0, v0, bstats), x, y, measured_ms=ms)
result["roofline"] = {k: (round(v, 3) if isinstance(v, float) else v)
                      for k, v in rl.items()}

# --- best-effort device trace -------------------------------------------
trace_dir = os.path.join(ROOT, "traces", "resnet50")
try:
    os.makedirs(trace_dir, exist_ok=True)
    st = (params, m0, v0, bstats)
    with jax.profiler.trace(trace_dir):
        for i in range(3):
            st = one(st, x, y)
        jax.block_until_ready(st)
    files = []
    for dp, _, fn in os.walk(trace_dir):
        files += [os.path.join(os.path.relpath(dp, ROOT), f) for f in fn]
    result["trace"] = {"ok": True, "files": files[:20]}
except Exception as e:
    result["trace"] = {"ok": False, "error": f"{type(e).__name__}: {e}"}

out = os.path.join(ROOT, "RESNET50_ROOFLINE.json" if ON_TPU
                   else "RESNET50_ROOFLINE_SMOKE.json")
bench.atomic_write_json(out, result)
print(json.dumps({k: result[k] for k in
                  ("measured_step_ms", "imgs_per_sec", "roofline")}))
if not ON_TPU and os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError("roofline ran on CPU")
