"""Commit gate for self-applying chip jobs (VERDICT r4 item 8).

q080/q085 patch kernel source and ``git commit`` autonomously. Before any
such commit, run the fast flash/softmax/Adam parity subset of the unit
suite (CPU, interpret mode) in a subprocess so a corrupt sweep artifact or
a block combination that breaks a non-bench shape can never be committed.
The gate result is recorded in the job's applied-defaults artifact.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

# APEX_TPU_ROOT keeps the gate, revert, and commit operating on the SAME
# tree as the jobs when the queue is dry-run from copied job files
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# fast, targeted: the tests that exercise the exact kernels the
# self-applying jobs patch (flash blocks, softmax, fused Adam)
GATE_TESTS = [
    "tests/test_flash_attention.py",
    "tests/test_transformer_ops.py",   # megatron softmax family
    "tests/test_fused_optimizers.py::TestFusedAdam",
]


def run_test_gate(tests: list[str] | None = None,
                  timeout_s: float = 900.0) -> dict:
    """Run the parity-test subset on CPU; return {ok, rc, wall_s, tail}.

    Runs in a subprocess with the axon hook stripped (sanitized_cpu_env)
    so the gate can never touch the TPU relay the calling worker holds.
    """
    sys.path.insert(0, ROOT)
    from __graft_entry__ import sanitized_cpu_env

    env = sanitized_cpu_env()  # CPU ⇒ kernels run in interpret mode
    cmd = [sys.executable, "-m", "pytest", "-x", "-q",
           *(tests or GATE_TESTS)]
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, cwd=ROOT, env=env,
                              capture_output=True, text=True,
                              timeout=timeout_s)
        rc, tail = proc.returncode, (proc.stdout + proc.stderr)[-2000:]
    except subprocess.TimeoutExpired as e:
        rc = -1
        tail = f"gate timeout after {timeout_s}s: " + \
            ((e.stdout or b"").decode("utf-8", "replace")[-1500:]
             if isinstance(e.stdout, bytes) else str(e.stdout)[-1500:])
    return {"ok": rc == 0, "rc": rc,
            "wall_s": round(time.time() - t0, 1), "tail": tail,
            "tests": tests or GATE_TESTS}


def revert_file(path: str) -> None:
    """Drop an uncommitted patch to ``path`` (gate failed)."""
    subprocess.run(["git", "checkout", "--", path], cwd=ROOT, check=True)


def gated_commit(kpath: str, message: str) -> dict:
    """Shared q080/q085 flow: run the parity gate on the already-patched
    ``kpath``; revert on failure, RAISE on gate timeout (transient — the
    worker's retry-with-backoff should re-run the job), commit on pass.
    Returns {applied, gate}."""
    gate = run_test_gate()
    if gate["rc"] == -1:
        revert_file(kpath)
        raise AssertionError(
            f"commit gate timed out: {gate['tail'][-300:]}")
    if not gate["ok"]:
        revert_file(kpath)
        return {"applied": False, "gate": gate}
    subprocess.run(["git", "add", "--", kpath], cwd=ROOT, check=True)
    subprocess.run(["git", "commit", "-q", "-m", message, "--", kpath],
                   cwd=ROOT, check=True)
    return {"applied": True, "gate": gate}
