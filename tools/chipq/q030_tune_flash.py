"""Chip job: flash-attention block sweep -> tools/tune_flash.out."""

import os
import sys

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if jax.default_backend() != "tpu" and \
        os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError("backend is not tpu; sweep would be meaningless")

sys.path.insert(0, os.path.join(ROOT, "tools"))
import tune_flash  # noqa: E402

# CPU (smoke) runs must never pollute the real sweep file q080 reads
name = ("tune_flash.out" if jax.default_backend() == "tpu"
        else "tune_flash_smoke.out")
with open(os.path.join(ROOT, "tools", name), "a") as f:
    best = tune_flash.run_sweep(jax, jnp, out=f)
if jax.default_backend() != "tpu":
    raise AssertionError("sweep ran on CPU")
if best is None:
    raise AssertionError("sweep produced no successful config")
