"""Chip job: the full bench suite -> BENCH_TPU_CACHE.json (incremental).

bench.py's driver-facing main() emits this capture in milliseconds, so the
driver window can never time out waiting on the relay again.
"""

import os
import sys

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bench  # noqa: E402

backend = jax.default_backend()
if backend != "tpu" and os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError(f"backend={backend}: refusing to burn the queue "
                         "on an interpret-mode suite")
out = os.path.join(ROOT, "BENCH_TPU_CACHE.json" if backend == "tpu"
                   else "BENCH_SMOKE.json")
suite = bench.run_suite(jax, jnp, backend, out_path=out)
bad = [n for n, _ in bench.BENCHES
       if "error" in suite.get(n, {"error": "missing"})]
if backend != "tpu":
    raise AssertionError("suite ran on CPU, not an acceptance capture")
if bad:
    raise AssertionError(f"benches failed: {bad}")
