"""Chip job: full-scale on-chip L1 amp-matrix slice (VERDICT r3 item 7).

ResNet-50, b128 @ 224px, ~50 steps, O1 (bf16 compute + dynamic loss scale)
vs O0 (fp32 compute), identical init and data stream — the TPU analog of
the reference L1 tier's dumped-tensor run comparison
(/root/reference/tests/L1/common/compare.py:12-40: two runs' loss curves
compared step-by-step under a tolerance). Writes L1_AMP_SLICE.json
incrementally (per-run curves as they finish).

Recipe follows main_amp.py:153-154: SGD momentum 0.9, wd 1e-4,
lr = 0.1 * batch/256.
"""

import json
import os
import sys
import time

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from apex_tpu.amp.grad_scaler import DynamicGradScaler  # noqa: E402
from apex_tpu.models.resnet import ResNet18ish, ResNet50  # noqa: E402
from apex_tpu.optimizers.functional import sgd_update  # noqa: E402

backend = jax.default_backend()
ON_TPU = backend == "tpu"
STEPS = 50 if ON_TPU else 6
BATCH, HW, NCLS = (128, 224, 1000) if ON_TPU else (8, 32, 10)
OUT = os.path.join(ROOT, "L1_AMP_SLICE.json" if ON_TPU
                   else "L1_AMP_SLICE_SMOKE.json")

result = {"backend": backend, "steps": STEPS, "batch": BATCH, "px": HW,
          "recipe": "sgd m0.9 wd1e-4 lr 0.1*b/256",
          "captured": time.strftime("%Y-%m-%dT%H:%M:%S")}


from bench import atomic_write_json  # noqa: E402


def _flush():
    atomic_write_json(OUT, result)


def run(opt_level):
    model = (ResNet50 if ON_TPU else ResNet18ish)(
        num_classes=NCLS,
        compute_dtype=jnp.bfloat16 if opt_level == "O1" else jnp.float32)
    x0 = jax.random.normal(jax.random.PRNGKey(0), (BATCH, HW, HW, 3),
                           jnp.float32)
    variables = model.init(jax.random.PRNGKey(2), x0)
    params, bstats = variables["params"], variables["batch_stats"]
    mom = jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p, jnp.float32), params)
    scaler = DynamicGradScaler() if opt_level == "O1" else None
    sstate = scaler.init() if scaler else None
    lr = 0.1 * BATCH / 256.0

    def loss_fn(p, bstats, x, y, scale):
        logits, updated = model.apply(
            {"params": p, "batch_stats": bstats}, x,
            mutable=["batch_stats"])
        onehot = jax.nn.one_hot(y, logits.shape[-1])
        loss = -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits.astype(jnp.float32)) * onehot,
            axis=-1))
        return loss * scale, (loss, updated["batch_stats"])

    @jax.jit
    def train_step(params, mom, bstats, sstate, x, y):
        scale = sstate.scale if sstate is not None else jnp.float32(1.0)
        grads, (loss, bs2) = jax.grad(loss_fn, has_aux=True)(
            params, bstats, x, y, scale)
        if sstate is not None:
            grads, found_inf = scaler.unscale(grads, sstate)
            sstate = scaler.update(sstate, found_inf)
        else:
            found_inf = jnp.zeros((), jnp.bool_)
        p2, m2 = sgd_update(params, grads, mom, lr=lr, momentum=0.9,
                            weight_decay=1e-4)
        keep = found_inf
        params = jax.tree_util.tree_map(
            lambda old, new: jnp.where(keep, old, new), params, p2)
        mom = jax.tree_util.tree_map(
            lambda old, new: jnp.where(keep, old, new), mom, m2)
        return params, mom, bs2, sstate, loss

    # a FIXED batch for every step: a memorization curve falls
    # deterministically (fresh random data has nothing learnable), which is
    # what makes the O0-vs-O1 curve comparison discriminative
    kx, ky = jax.random.split(jax.random.PRNGKey(1000))
    x = jax.random.normal(kx, (BATCH, HW, HW, 3), jnp.float32)
    y = jax.random.randint(ky, (BATCH,), 0, NCLS, jnp.int32)
    losses = []
    for s in range(STEPS):
        params, mom, bstats, sstate, loss = train_step(
            params, mom, bstats, sstate, x, y)
        losses.append(float(loss))
    return losses, params


t0 = time.time()
losses_o0, params_o0 = run("O0")
result["O0"] = {"losses": [round(v, 5) for v in losses_o0],
                "wall_s": round(time.time() - t0, 1)}
_flush()
t0 = time.time()
losses_o1, params_o1 = run("O1")
result["O1"] = {"losses": [round(v, 5) for v in losses_o1],
                "wall_s": round(time.time() - t0, 1)}
_flush()

a = np.asarray(losses_o0)
b = np.asarray(losses_o1)
rel = np.abs(a - b) / np.maximum(np.abs(a), 1e-6)
wa = np.concatenate([np.ravel(np.asarray(x, np.float32))
                     for x in jax.tree_util.tree_leaves(params_o0)])
wb = np.concatenate([np.ravel(np.asarray(x, np.float32))
                     for x in jax.tree_util.tree_leaves(params_o1)])
wrel = float(np.linalg.norm(wa - wb) / (np.linalg.norm(wa) + 1e-12))
# compare.py-style tolerance verdict: amp run must track fp32 closely on
# the same data; both must actually train (loss falls)
result["mean_rel_loss_diff"] = round(float(rel.mean()), 5)
result["max_rel_loss_diff"] = round(float(rel.max()), 5)
result["end_weight_rel_diff"] = round(wrel, 5)
result["o0_trains"] = bool(a[-1] < a[0])
result["o1_trains"] = bool(b[-1] < b[0])
result["pass"] = bool(rel.mean() < 0.05 and wrel < 0.05
                      and a[-1] < a[0] and b[-1] < b[0])
_flush()
print(json.dumps({k: result[k] for k in
                  ("mean_rel_loss_diff", "end_weight_rel_diff", "pass")}))
allow_cpu = os.environ.get("CHIPQ_ALLOW_CPU") == "1"
if not (result["pass"] and (ON_TPU or allow_cpu)):
    raise AssertionError(f"L1 slice: pass={result['pass']} "
                         f"backend={backend}")
