"""Chip job: causal softmax — chunked-fetch kernel vs row-complete kernel.

Measures the megatron-path causal softmax at the bench shape through the
public entry (routes to the chunked kernel) and with the chunked path
disabled, so the round-4 DMA-elision claim is backed by an on-chip A/B.
Appends JSON lines to tools/tune_softmax.out.
"""

import json
import os
import sys
import time

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

if jax.default_backend() != "tpu" and \
        os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError("backend is not tpu")

from apex_tpu.ops.pallas import softmax_kernel as sk  # noqa: E402
from apex_tpu.utils.benchtime import (measure_fetch_floor,  # noqa: E402
                                      timed_steps)

ON_TPU = jax.default_backend() == "tpu"
gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
peak_gbps = {"v5e": 819.0, "v6e": 1640.0, "v5p": 2765.0}.get(gen, 819.0)
b, h, s = (8, 16, 1024) if ON_TPU else (1, 2, 256)
iters = 50 if ON_TPU else 2
floor_s = measure_fetch_floor()

x = jax.random.normal(jax.random.PRNGKey(0), (b * h, s, s),
                      jnp.bfloat16) * 0.1


def run_variant(chunked: bool):
    orig = sk._softmax_fwd_causal_chunked
    if not chunked:
        sk._softmax_fwd_causal_chunked = lambda *a, **k: None
    try:
        def step(i, x3):
            return sk.softmax_fwd_pallas(
                x3, None, scale=0.5, causal=True,
                interpret=not ON_TPU).astype(x3.dtype)

        # donate=False: x is shared by both variants (a donated buffer
        # would be deleted after the first)
        return timed_steps(step, x, iters=iters, floor_s=floor_s,
                           donate=False)
    finally:
        sk._softmax_fwd_causal_chunked = orig


results = []
_oname = ("tune_softmax.out" if ON_TPU else "tune_softmax_smoke.out")
with open(os.path.join(ROOT, "tools", _oname), "a") as out:
    print(f"# backend={jax.default_backend()} b{b}h{h}s{s}", file=out,
          flush=True)
    for name, chunked in [("chunked", True), ("row_complete", False)]:
        try:
            t0 = time.time()
            ms = run_variant(chunked)
            frac = x.size * 2 * 2 / (ms / 1e3) / 1e9 / peak_gbps
            rec = {"variant": name, "ms": round(ms, 3),
                   "hbm_frac_full_matrix": round(frac, 3),
                   "wall_s": round(time.time() - t0, 1)}
            results.append(rec)
            print(json.dumps(rec), file=out, flush=True)
        except Exception as e:
            print(json.dumps({"variant": name,
                              "error": f"{type(e).__name__}: {e}"}),
                  file=out, flush=True)
    print(json.dumps({"results": results}), file=out, flush=True)
if not results:
    raise AssertionError("no successful variant")
