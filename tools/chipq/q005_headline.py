"""Chip job: capture ONLY the headline fused-Adam@1B row, fast.

Insurance for a late-returning relay: lands a real TPU record in
BENCH_TPU_CACHE.json minutes after acquisition (complete=false — the full
q020 suite overwrites it). bench.py's worker-poll path accepts a partial
capture at its deadline, so even a worker still mid-suite at driver time
yields a TPU-backed headline.
"""

import os
import sys
import time

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bench  # noqa: E402

backend = jax.default_backend()
if backend != "tpu" and os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError(f"backend={backend}")

from apex_tpu.utils.benchtime import measure_fetch_floor  # noqa: E402

gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
chip = bench._CHIP.get(gen, bench._CHIP["v5e"])
floor_s = measure_fetch_floor()
entry = bench.bench_fused_adam(jax, jnp, backend == "tpu", chip, floor_s)
suite = {"backend": backend, "chip": gen, "complete": False,
         "captured": time.strftime("%Y-%m-%dT%H:%M:%S"),
         "note": "headline-only early capture (q005); q020 overwrites",
         "fused_adam_1b": entry}
out = os.path.join(ROOT, "BENCH_TPU_CACHE.json" if backend == "tpu"
                   else "BENCH_SMOKE_HEADLINE.json")
bench.atomic_write_json(out, suite)
print(entry)
