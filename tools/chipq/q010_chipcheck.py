"""Chip job: compiled-kernel parity artifact (CHIPCHECK.json).

Runs chipcheck.run_checks against the worker's already-initialized backend.
Writes incrementally; raises if any kernel fails so the done-marker records
the failure.
"""

import os
import sys

# APEX_TPU_ROOT lets the queue dry-run execute COPIES of these jobs from
# a throwaway dir while still resolving repo artifacts correctly
ROOT = os.environ.get("APEX_TPU_ROOT") or os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402  (already initialized by the worker)
import jax.numpy as jnp  # noqa: E402

import chipcheck  # noqa: E402

backend = jax.default_backend()
if backend != "tpu" and os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError(f"backend={backend}: chipcheck must run compiled "
                         "on the chip")
out = os.path.join(ROOT, "CHIPCHECK.json" if backend == "tpu"
                   else "CHIPCHECK_SMOKE.json")
results = chipcheck.run_checks(jax, jnp, backend, out_path=out)
failed = [n for n, _ in chipcheck.CHECKS
          if not results.get(n, {}).get("pass")]
# on TPU the artifact's own ok flag is the contract; on an allowed-CPU
# dry-run only actual check failures count (run_checks pins ok=False for
# any non-TPU backend by design)
if failed or (backend == "tpu" and not results.get("ok")):
    raise AssertionError(f"chipcheck not ok (backend={backend}, "
                         f"failed={failed})")
