"""Chip job: compiled-kernel parity artifact (CHIPCHECK.json).

Runs chipcheck.run_checks against the worker's already-initialized backend.
Writes incrementally; raises if any kernel fails so the done-marker records
the failure.
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

import jax  # noqa: E402  (already initialized by the worker)
import jax.numpy as jnp  # noqa: E402

import chipcheck  # noqa: E402

backend = jax.default_backend()
if backend != "tpu" and os.environ.get("CHIPQ_ALLOW_CPU") != "1":
    raise AssertionError(f"backend={backend}: chipcheck must run compiled "
                         "on the chip")
out = os.path.join(ROOT, "CHIPCHECK.json" if backend == "tpu"
                   else "CHIPCHECK_SMOKE.json")
results = chipcheck.run_checks(jax, jnp, backend, out_path=out)
if not results.get("ok"):
    failed = [n for n, _ in chipcheck.CHECKS
              if not results.get(n, {}).get("pass")]
    raise AssertionError(f"chipcheck not ok (backend={backend}, "
                         f"failed={failed})")
