"""Shared scaffolding for the deviceless AOT tools (mosaic/model/stack).

Importing this module (BEFORE anything else imports jax) puts the
process into compile-only mode: kernels lower via Mosaic rather than
interpret (APEX_TPU_FORCE_COMPILED), libtpu's host probing is quieted,
the host backend is pinned to CPU so the axon relay is never touched,
and the persistent compile cache is enabled so artifact refreshes skip
recompilation. One copy of this setup — the three tools were drifting.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

# kernels must pick the compiled (Mosaic) lowering even though the
# default backend is CPU — see apex_tpu/utils/env.py:interpret_default
os.environ["APEX_TPU_FORCE_COMPILED"] = "1"
# quiet libtpu's host-metadata probing (no real TPU VM here)
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-4")
os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # host stays off the relay
try:  # persistent cache: deviceless AOT compiles are cache-keyed
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(ROOT, ".jax_cache"))
except Exception:
    pass

from bench import atomic_write_json  # noqa: E402,F401


def get_topology(default: str = "v5e:2x2"):
    """The compile-only topology (MOSAIC_AOT_TOPOLOGY overrides)."""
    from jax.experimental import topologies

    return topologies.get_topology_desc(
        os.environ.get("MOSAIC_AOT_TOPOLOGY", default), "tpu")
