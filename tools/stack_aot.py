"""Deviceless v5e AOT compile of the DISTRIBUTED stack.

Third leg of the AOT evidence tripod (mosaic_aot.py = Pallas kernel zoo,
model_aot.py = single-chip headline models): compiles the multi-chip
training paths against a compile-only 4-device v5e:2x2 client built from
the baked-in libtpu — the ZeRO optimizers (DistributedFusedAdam in all
four state layouts + the 2D redundant grid, DistributedFusedLAMB in both
grad-sync modes and both clip points), the Megatron-style TP×SP GPT-2
train step, the composed 1F1B pipeline + MoE step, and the DDP/SyncBN/
Ulysses shard_map paths. Until now these had only ever compiled for
virtual CPU meshes; this proves the real-TPU lowering (collectives,
layouts, HLO partitioning) with no chip attached.

ZeRO optimizers are instantiated with ``abstract_state=True`` (state as
sharded shape structs — no runtime buffers exist on a compile-only
client). Output: STACK_AOT.json, kept green by tests/test_stack_aot.py.
"""

from __future__ import annotations

import json
import os
import sys
import time

# shared compile-only scaffolding (env + CPU pin + cache) — must import
# before jax backend use
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _aot_common import (ROOT, atomic_write_json,  # noqa: E402
                         get_topology)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import Mesh, NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from apex_tpu.utils.compat import shard_map  # noqa: E402

OUT_PATH = os.environ.get("STACK_AOT_OUT",
                          os.path.join(ROOT, "STACK_AOT.json"))

_f32 = jnp.float32


def _params():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return [jax.random.normal(ks[0], (4096, 128)) * 0.1,
            jax.random.normal(ks[1], (4096,)) * 0.1,
            jax.random.normal(ks[2], (1024, 256)) * 0.1]


def _gstructs(params, sharding=None):
    """Shape structs for grads. By default UNPINNED (no sharding): pinning
    grads replicated at the jit boundary would forbid the partitioner from
    ever emitting the RS+AR mode's reduce-scatter, turning a harness
    artifact into a fake 'modes compile identically' finding."""
    if sharding is None:
        return jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params)
    return jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype, sharding=sharding),
        params)


def compile_dist_adam(mesh, **kw):
    from apex_tpu.optimizers.distributed_fused_adam import \
        DistributedFusedAdam

    params = _params()
    dopt = DistributedFusedAdam(params, mesh, lr=1e-3, weight_decay=0.01,
                                abstract_state=True, **kw)
    jit_tree, _ = dopt._build_step()
    grads = _gstructs(params)
    vecs = dopt._group_vectors(1e-3)
    return jit_tree.lower(dopt._state_pack(), grads, jnp.int32(1),
                          _f32(1.0), jnp.asarray(False), *vecs).compile()


def compile_dist_lamb(mesh, **kw):
    from apex_tpu.optimizers.distributed_fused_lamb import \
        DistributedFusedLAMB

    params = _params()
    dopt = DistributedFusedLAMB(params, mesh, lr=1e-3, weight_decay=0.01,
                                max_grad_norm=1.0, abstract_state=True, **kw)
    jit = dopt._build()
    grads = _gstructs(params)
    return jit.lower(dopt._master, dopt._m, dopt._v, grads, None,
                     jnp.int32(1), _f32(1e-3), _f32(1.0),
                     jnp.asarray(False)).compile()


def compile_gpt2_tp_sp(mesh4):
    from apex_tpu.models.gpt2 import GPT2Config
    from apex_tpu.models.gpt2_parallel import (init_opt_state, init_params,
                                               make_train_step)

    seq = 256
    cfg = GPT2Config(vocab_size=512, n_positions=seq, n_embd=128,
                     n_layer=2, n_head=4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt_state = init_opt_state(params)
    step = make_train_step(cfg, mesh4, lr=1e-4)
    tokens = jnp.zeros((2, seq), jnp.int32)
    mask = jnp.ones((2, seq), jnp.float32)
    return step.lower(params, opt_state, tokens, tokens, mask,
                      jnp.int32(1)).compile()


def compile_gpt2_pp_tp(mesh5):
    from apex_tpu.models.gpt2 import GPT2Config
    from apex_tpu.models.gpt2_parallel import (init_opt_state,
                                               init_params_pp,
                                               make_train_step_pp)

    seq = 256
    cfg = GPT2Config(vocab_size=512, n_positions=seq, n_embd=128,
                     n_layer=2, n_head=4)
    p5 = init_params_pp(cfg, jax.random.PRNGKey(7), moe_experts=2)
    st5 = init_opt_state(p5)
    step = make_train_step_pp(cfg, mesh5, lr=1e-4, num_microbatches=2,
                              moe_experts=2)
    tokens = jnp.zeros((2, seq), jnp.int32)
    mask = jnp.ones((2, seq), jnp.float32)
    return step.lower(p5, st5, tokens, tokens, mask, jnp.int32(1)).compile()


def compile_ddp_syncbn(mesh4):
    from apex_tpu.parallel.ddp import bucketed_allreduce
    from apex_tpu.parallel.sync_batch_norm import sync_batch_norm_stats

    def body(grads, x):
        g = bucketed_allreduce(grads, axis_name="data")
        mean, var, cnt = sync_batch_norm_stats(x, (0, 1, 2), "data")
        return g, mean, var, cnt

    ns = NamedSharding(mesh4, P("data"))
    grads = _gstructs(_params(), ns)
    x = jax.ShapeDtypeStruct((8, 8, 8, 64), jnp.float32, sharding=ns)
    fn = jax.jit(shard_map(
        body, mesh=mesh4, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P(), P(), P()), check_vma=False))
    return fn.lower(grads, x).compile()


def compile_ulysses(mesh4):
    from apex_tpu.parallel.ulysses import ulysses_self_attention

    ns = NamedSharding(mesh4, P(None, None, "data", None))
    q = jax.ShapeDtypeStruct((1, 8, 4 * 512, 64), jnp.bfloat16, sharding=ns)
    fn = jax.jit(shard_map(
        lambda q, k, v: ulysses_self_attention(q, k, v, "data", True),
        mesh=mesh4, in_specs=P(None, None, "data", None),
        out_specs=P(None, None, "data", None), check_vma=False))
    return fn.lower(q, q, q).compile()


def compile_ring_long(mesh16, zigzag: bool):
    """Long-context story at real scale: 131k tokens of causal ring /
    zigzag attention sharded over a 16-chip, 4-HOST v5e:4x4 topology —
    the multi-host partitioning path the reference reaches with NCCL."""
    from apex_tpu.parallel.ring_attention import (
        ring_attention, zigzag_ring_self_attention)

    n = mesh16.shape["sp"]
    s_total = n * 8192  # 131072 tokens over 16 chips
    ns = NamedSharding(mesh16, P(None, None, "sp", None))
    q = jax.ShapeDtypeStruct((1, 8, s_total, 128), jnp.bfloat16,
                             sharding=ns)
    if zigzag:
        body = lambda q, k, v: zigzag_ring_self_attention(  # noqa: E731
            q, k, v, "sp")
    else:
        body = lambda q, k, v: ring_attention(  # noqa: E731
            q, k, v, axis_name="sp", causal=True)
    fn = jax.jit(shard_map(
        body, mesh=mesh16, in_specs=P(None, None, "sp", None),
        out_specs=P(None, None, "sp", None), check_vma=False))
    return fn.lower(q, q, q).compile()


def compile_zero_adam_16dev(mesh16d):
    """ZeRO-2 Adam sharded over 16 chips / 4 hosts at 64M params."""
    from apex_tpu.optimizers.distributed_fused_adam import \
        DistributedFusedAdam

    params = [jnp.zeros((8192, 4096), jnp.float32),
              jnp.zeros((8192 * 4096,), jnp.float32)]
    dopt = DistributedFusedAdam(params, mesh16d, lr=1e-3,
                                store_param_remainders=True,
                                abstract_state=True)
    jit_tree, _ = dopt._build_step()
    grads = _gstructs(params)
    vecs = dopt._group_vectors(1e-3)
    return jit_tree.lower(dopt._state_pack(), grads, jnp.int32(1),
                          _f32(1.0), jnp.asarray(False), *vecs).compile()


def main():
    t0 = time.time()
    topo = get_topology()
    devs = np.array(topo.devices[:4])
    mesh_data = Mesh(devs.reshape(4), ("data",))
    mesh_2d = Mesh(devs.reshape(2, 2), ("data", "rep"))
    from apex_tpu.parallel.mesh import make_mesh

    mesh_tp_sp = make_mesh([1, 2, 2], ["dp", "tp", "sp"], list(devs))
    mesh5 = make_mesh([1, 2, 2, 1, 1], ["dp", "pp", "tp", "sp", "ep"],
                      list(devs))
    # 16-chip, 4-HOST topology for the long-context / ZeRO-at-scale cases
    topo16 = topologies.get_topology_desc("v5e:4x4", "tpu")
    devs16 = np.array(topo16.devices)
    mesh16_sp = Mesh(devs16.reshape(16), ("sp",))
    mesh16_d = Mesh(devs16.reshape(16), ("data",))

    CASES = [
        ("dist_adam_base", lambda: compile_dist_adam(mesh_data)),
        ("dist_adam_param_remainders",
         lambda: compile_dist_adam(mesh_data,
                                   store_param_remainders=True)),
        ("dist_adam_scaled_states",
         lambda: compile_dist_adam(mesh_data, with_scaled_states=True)),
        ("dist_adam_grad_clip",
         lambda: compile_dist_adam(mesh_data, max_grad_norm=1.0)),
        ("dist_adam_2d_redundant",
         lambda: compile_dist_adam(mesh_2d, redundant_axis="rep")),
        ("dist_lamb_rs_ar", lambda: compile_dist_lamb(mesh_data)),
        ("dist_lamb_full_ar",
         lambda: compile_dist_lamb(mesh_data, full_ar=True)),
        ("dist_lamb_clip_before_ar",
         lambda: compile_dist_lamb(mesh_data, clip_after_ar=False)),
        ("gpt2_tp2_sp2_train", lambda: compile_gpt2_tp_sp(mesh_tp_sp)),
        ("gpt2_pp2_tp2_moe_train", lambda: compile_gpt2_pp_tp(mesh5)),
        ("ddp_syncbn_4dev", lambda: compile_ddp_syncbn(mesh_data)),
        ("ulysses_attention_4dev", lambda: compile_ulysses(mesh_data)),
        ("ring_attention_131k_16dev_4host",
         lambda: compile_ring_long(mesh16_sp, zigzag=False)),
        ("zigzag_attention_131k_16dev_4host",
         lambda: compile_ring_long(mesh16_sp, zigzag=True)),
        ("zero_adam_64m_16dev_4host",
         lambda: compile_zero_adam_16dev(mesh16_d)),
    ]

    result = {"device_kind": getattr(topo.devices[0], "device_kind", "?"),
              "jax": jax.__version__,
              "captured": time.strftime("%Y-%m-%dT%H:%M:%S"),
              "cases": {}}
    ok_all = True
    for name, fn in CASES:
        t1 = time.time()
        try:
            compiled = fn()
            entry = {"ok": True}
            try:
                import re

                txt = compiled.as_text()
                # definition sites only: "op(" / "op-start(" — plain
                # substring counts would also hit operand references
                # (%all-gather.5) and double-count async pairs
                entry["collectives"] = {
                    op: len(re.findall(op + r"(?:-start)?\(", txt)) for op in
                    ("all-reduce", "reduce-scatter", "all-gather",
                     "collective-permute", "all-to-all")}
            except Exception:
                pass
        except Exception as e:
            entry = {"ok": False,
                     "error": f"{type(e).__name__}: {str(e)[:1200]}"}
            ok_all = False
        entry["wall_s"] = round(time.time() - t1, 1)
        result["cases"][name] = entry
        print(f"[stack_aot] {name} "
              f"{'OK' if entry['ok'] else 'FAIL ' + entry.get('error', '')}"
              f" ({entry['wall_s']}s)", file=sys.stderr, flush=True)
        result["ok"] = False
        result["wall_s"] = round(time.time() - t0, 1)
        atomic_write_json(OUT_PATH, result)
    result["ok"] = ok_all
    result["wall_s"] = round(time.time() - t0, 1)
    atomic_write_json(OUT_PATH, result)
    print(json.dumps({"ok": ok_all, "cases": len(CASES),
                      "wall_s": result["wall_s"]}))
    sys.exit(0 if ok_all else 2)


if __name__ == "__main__":
    main()
