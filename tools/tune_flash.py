"""Flash-attention block-size sweep on the real chip (VERDICT r2 item 2).

Measures fwd and fwd+bwd TFLOPs of ops/pallas/flash_attention.py across
(block_q, block_k) configurations at the bench shape (b4·h16·s2048·d64,
bf16, causal) plus a d=128 reference point, printing one JSON line per
config AS IT COMPLETES (python -u; the relay can die mid-sweep and earlier
lines survive). Run unbounded in the background — never under `timeout`
(killing a TPU-holding process wedges the relay).

    nohup python -u tools/tune_flash.py > tools/tune_flash.out 2>&1 &

NOTE: the general successor is ``apex-tpu-tune`` (apex_tpu/tune), which
sweeps the same flash block set (registry._FA_BLOCKS), persists winners to
the shape-keyed tune cache that ``flash_attention`` consults at trace
time, and covers the rest of the kernel zoo; this script remains the
deep-dive harness (fwd+bwd TFLOPs, d=128 point, jax-pallas ceiling
comparator) whose findings inform the registry's candidate set.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_sweep(jax, jnp, out=sys.stdout):
    """Run the block sweep against an already-initialized backend, printing
    one JSON line per config to ``out`` as it completes. Callable from the
    background chip worker without re-probing the relay."""
    from apex_tpu.ops.pallas.flash_attention import flash_attention
    from apex_tpu.utils.benchtime import measure_fetch_floor, timed_steps

    def emit(obj):
        print(json.dumps(obj), file=out, flush=True)

    backend = jax.default_backend()
    print(f"# backend={backend}", file=out, flush=True)
    on_tpu = backend == "tpu"
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    peak = {"v5e": 197.0, "v6e": 918.0, "v5p": 459.0}.get(gen, 197.0)
    floor_s = measure_fetch_floor()

    def measure(b, h, s, d, iters, attn_fn, **tag):
        """Time fwd and fwd+bwd of ``attn_fn(q, k, v)`` (causal) at the
        given shape; ``tag`` entries are merged into the result record.
        One timing/FLOPs implementation shared by our sweep configs AND
        the ceiling comparator, so they can never diverge."""
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(k_, (b, h, s, d), jnp.bfloat16) * 0.2
                   for k_ in ks)

        def fwd_step(i, q, k, v):
            return attn_fn(q, k, v).astype(q.dtype)

        ms_fwd = timed_steps(fwd_step, q, iters=iters, consts=(k, v),
                             floor_s=floor_s, donate=False)

        gradfn = jax.grad(lambda q, k, v: jnp.sum(
            attn_fn(q, k, v).astype(jnp.float32) ** 2))

        def bwd_step(i, q, k, v):
            return (q + 1e-3 * gradfn(q, k, v).astype(q.dtype)) \
                .astype(q.dtype)

        ms_fb = timed_steps(bwd_step, q, iters=iters, consts=(k, v),
                            floor_s=floor_s, donate=False)

        flops_fwd = 2 * 2 * b * h * s * s * d / 2  # causal
        # bwd ≈ 2.5x fwd FLOPs (dq, dk, dv + recompute); fwd+bwd total 3.5x
        tflops_fwd = flops_fwd / (ms_fwd / 1e3) / 1e12
        tflops_fb = 3.5 * flops_fwd / (ms_fb / 1e3) / 1e12
        return {"shape": f"b{b}h{h}s{s}d{d}", **tag,
                "fwd_ms": round(ms_fwd, 3), "fwd_tflops": round(tflops_fwd, 1),
                "fwd_mxu": round(tflops_fwd / peak, 3),
                "fb_ms": round(ms_fb, 3), "fb_tflops": round(tflops_fb, 1),
                "fb_mxu": round(tflops_fb / peak, 3)}

    def ours(bq, bk):
        return lambda q, k, v: flash_attention(q, k, v, True, block_q=bq,
                                               block_k=bk)

    b, h, s, d = (4, 16, 2048, 64) if on_tpu else (1, 2, 256, 64)
    iters = 20 if on_tpu else 2
    # (1024,2048)/(2048,1024)/(2048,2048) are excluded: their BACKWARD
    # exceeds v5e VMEM (proven deviceless — tools/flash_blocks_aot.json,
    # Mosaic RESOURCE_EXHAUSTED on the dkv transpose scratch); a sweep
    # winner must be usable for fwd AND bwd since q080 applies it to both
    blocks = ([(256, 256), (256, 512), (512, 512), (512, 1024),
               (1024, 512), (1024, 1024), (2048, 512), (512, 2048),
               (256, 2048), (128, 1024), (128, 2048), (256, 1024),
               (128, 512)]
              if on_tpu else [(128, 128), (256, 128)])
    best = None
    for bq, bk in blocks:
        if bq > s or bk > s:
            continue
        try:
            t0 = time.perf_counter()
            r = measure(b, h, s, d, iters, ours(bq, bk), bq=bq, bk=bk)
            r["wall_s"] = round(time.perf_counter() - t0, 1)
            emit(r)
            if best is None or r["fwd_tflops"] > best["fwd_tflops"]:
                best = r
        except Exception as e:
            emit({"bq": bq, "bk": bk,
                  "error": f"{type(e).__name__}: {e}"})
    if on_tpu and best is not None:
        # d=128 reference point at the winning blocks
        try:
            r = measure(4, 8, 2048, 128, iters,
                        ours(best["bq"], best["bk"]),
                        bq=best["bq"], bk=best["bk"])
            emit(r)
        except Exception as e:
            emit({"shape": "d128", "error": str(e)})

    # ceiling comparator: jax's own Pallas TPU flash kernel at the same
    # shape — what a heavily-tuned kernel achieves on THIS chip. If ours
    # tracks it, the residual vs the MXU peak is platform, not our kernel.
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention as jfa

        sm = 1.0 / (d ** 0.5)
        r = measure(b, h, s, d, iters,
                    lambda q, k, v: jfa.flash_attention(
                        q, k, v, causal=True, sm_scale=sm),
                    comparator="jax.experimental.pallas flash_attention")
        emit(r)
    except Exception as e:
        emit({"comparator": "jax pallas flash",
              "error": f"{type(e).__name__}: {e}"})
    # stamp the backend into the best record: q080 must never apply block
    # defaults derived from a CPU (interpret-mode) sweep line
    emit({"best": best, "backend": backend})
    return best


def main():
    from bench import wait_for_backend

    if not wait_for_backend(tag="tune_flash"):
        print(json.dumps({"error": "backend unreachable"}))
        sys.exit(2)
    import jax
    import jax.numpy as jnp

    run_sweep(jax, jnp)


if __name__ == "__main__":
    main()
