"""Deviceless Mosaic AOT compile of the Pallas kernel zoo (VERDICT r4 item 2).

Four rounds of relay outages meant no Pallas kernel in this repo had ever
been compiled by Mosaic — interpret-mode parity (the test suite) is blind
to Mosaic compile errors, VMEM-budget violations, and layout problems.
This tool closes that hole WITHOUT the chip: the baked-in ``libtpu.so``
can build a compile-only PJRT client from a topology description
(``jax.experimental.topologies.get_topology_desc``), so every kernel is
lowered and compiled for a real v5e target with no attached device.

The reference compiles its kernel zoo in its build matrix
(/root/reference/tests/docker_extension_builds/run.sh:16-40); this is the
TPU analog, and it runs even when the axon relay is down — a dead relay
can no longer zero out a round's compile evidence.

Coverage mirrors chipcheck.py's 10 checks (same names, so the artifacts
line up), at the REAL bench shapes, fwd+bwd where the surface has a VJP,
plus the multi-device RDMA/ring paths compiled over a 4-device v5e:2x2
topology mesh (shard_map → Mosaic remote DMA — never compiled before).

Output: MOSAIC_AOT.json — per-kernel {compiled, tags: {tag: {ok, wall_s,
error?}}} + overall ``ok``. Exit 0 iff every tag compiled.
"""

from __future__ import annotations

import json
import os
import sys
import time

# shared compile-only scaffolding (env + CPU pin + cache) — must import
# before jax backend use
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _aot_common import (ROOT, atomic_write_json,  # noqa: E402
                         get_topology)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from apex_tpu.utils.compat import shard_map  # noqa: E402
from jax.sharding import SingleDeviceSharding  # noqa: E402

OUT_PATH = os.environ.get("MOSAIC_AOT_OUT",
                          os.path.join(ROOT, "MOSAIC_AOT.json"))


def _struct(shape, dtype, sharding):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)


def build_cases(dev_sharding, mesh):
    """Return [(kernel_name, tag, fn, args)] at bench shapes."""
    s = dev_sharding
    cases = []

    def add(kernel, tag, fn, *args):
        cases.append((kernel, tag, fn, args))

    LANE = 128

    # ---- flat optimizer kernels at the 1B-element bench shape ----------
    rows = 999_999_488 // LANE
    pb = _struct((rows, LANE), jnp.bfloat16, s)
    gb = _struct((rows, LANE), jnp.bfloat16, s)
    mf = _struct((rows, LANE), jnp.float32, s)
    vf = _struct((rows, LANE), jnp.float32, s)
    pf = _struct((rows, LANE), jnp.float32, s)

    from apex_tpu.ops.pallas.fused_adam_kernel import (ADAM_MODE_L2,
                                                       fused_adam_flat,
                                                       fused_adam_flat_master)
    add("fused_adam_flat", "adamw_1b",
        lambda p, g, m, v: fused_adam_flat(p, g, m, v, lr=1e-3,
                                           weight_decay=0.01, step=3),
        pb, gb, mf, vf)
    add("fused_adam_flat", "l2_1b",
        lambda p, g, m, v: fused_adam_flat(p, g, m, v, lr=1e-3,
                                           weight_decay=0.01, step=3,
                                           mode=ADAM_MODE_L2,
                                           inv_scale=0.5),
        pb, gb, mf, vf)
    add("fused_adam_flat", "master_1b",
        lambda p, g, m, v: fused_adam_flat_master(p, g, m, v, lr=1e-3,
                                                  weight_decay=0.01, step=3),
        pf, gb, mf, vf)

    from apex_tpu.ops.pallas.fused_sgd_kernel import fused_sgd_flat
    add("fused_sgd_flat", "momentum_wd_1b",
        lambda p, g, b: fused_sgd_flat(p, g, b, lr=0.1, momentum=0.9,
                                       weight_decay=1e-4, inv_scale=2.0),
        pb, gb, mf)

    from apex_tpu.ops.pallas.fused_opt_kernels import (fused_adagrad_flat,
                                                       fused_lamb_flat,
                                                       fused_novograd_flat)
    # LAMB/NovoGrad: segment-summed per-tensor norms — the bench/BERT path
    # runs ~1e8 elements over hundreds of tensors; compile with a
    # representative segment map (structure, not data, is what Mosaic sees)
    lrows = 104_857_600 // LANE
    rid = _struct((lrows,), jnp.int32, s)
    lp = _struct((lrows, LANE), jnp.float32, s)
    add("fused_lamb_flat", "bert_scale",
        lambda p, g, m, v, r: fused_lamb_flat(
            p, g, m, v, r, num_tensors=400, lr=1e-2, weight_decay=0.01,
            step=2, max_grad_norm=1.0),
        lp, lp, lp, lp, rid)
    vt = _struct((400,), jnp.float32, s)
    add("fused_novograd_flat", "bert_scale",
        lambda p, g, m, v, r: fused_novograd_flat(
            p, g, m, v, r, num_tensors=400, lr=1e-2, weight_decay=0.01,
            step=1),
        lp, lp, lp, vt, rid)
    add("fused_adagrad_flat", "1b",
        lambda p, g, h: fused_adagrad_flat(p, g, h, lr=1e-2,
                                           weight_decay=1e-4),
        pf, _struct(gb.shape, jnp.float32, s), mf)

    # ---- LayerNorm / RMSNorm at the bench shape (8192x4096 bf16) -------
    from apex_tpu.normalization.fused_layer_norm import (
        fused_layer_norm_affine, fused_rms_norm_affine)
    xln = _struct((8192, 4096), jnp.bfloat16, s)
    wln = _struct((4096,), jnp.float32, s)
    bln = _struct((4096,), jnp.float32, s)
    add("layer_norm", "fwd_8192x4096_bf16",
        lambda x, w, b: fused_layer_norm_affine(x, w, b, 4096), xln, wln, bln)
    add("layer_norm", "bwd_8192x4096_bf16",
        lambda x, w, b: jax.grad(
            lambda x, w, b: jnp.sum(
                fused_layer_norm_affine(x, w, b, 4096)
                .astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(x, w, b),
        xln, wln, bln)
    add("layer_norm", "bwd_memeff",
        lambda x, w, b: jax.grad(
            lambda x, w, b: jnp.sum(
                fused_layer_norm_affine(x, w, b, 4096,
                                        memory_efficient=True)
                .astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(x, w, b),
        xln, wln, bln)
    add("layer_norm", "rms_fwd",
        lambda x, w: fused_rms_norm_affine(x, w, 4096), xln, wln)

    # ---- GroupNorm NHWC (both algos, SiLU epilogue) --------------------
    from apex_tpu.ops.pallas.group_norm_kernel import group_norm_nhwc_pallas
    xgn = _struct((8, 32, 32, 256), jnp.float32, s)
    wgn = _struct((256,), jnp.float32, s)
    add("group_norm", "one_pass_silu",
        lambda x, w, b: group_norm_nhwc_pallas(x, 32, w, b, act="silu",
                                               algo="one_pass"),
        xgn, wgn, wgn)
    add("group_norm", "two_pass",
        lambda x, w, b: group_norm_nhwc_pallas(x, 32, w, b,
                                               algo="two_pass"),
        xgn, wgn, wgn)

    # ---- Megatron softmax kernels at the bench shape -------------------
    from apex_tpu.ops.pallas.softmax_kernel import (softmax_bwd_pallas,
                                                    softmax_fwd_pallas)
    B, sq = 128, 1024  # b8·h16 fused softmax bench shape
    xs = _struct((B, sq, sq), jnp.float32, s)
    ms = _struct((B, sq, sq), jnp.bool_, s)
    add("softmax", "causal_chunked_fwd",
        lambda x: softmax_fwd_pallas(x, None, scale=0.5, causal=True), xs)
    add("softmax", "masked_fwd",
        lambda x, m: softmax_fwd_pallas(x, m, scale=0.7, causal=False),
        xs, ms)
    add("softmax", "bwd",
        lambda y, dy: softmax_bwd_pallas(y, dy, scale=0.5), xs, xs)

    # ---- Flash attention at the headline bench shape -------------------
    from apex_tpu.ops.pallas.flash_attention import flash_attention
    b, h, sl, d = 4, 16, 2048, 64
    qs = _struct((b, h, sl, d), jnp.bfloat16, s)
    add("flash_attention", "causal_fwd_b4h16s2048",
        lambda q, k, v: flash_attention(q, k, v, True), qs, qs, qs)
    add("flash_attention", "causal_bwd_b4h16s2048",
        lambda q, k, v: jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, True).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v),
        qs, qs, qs)
    mask = _struct((b, 1, sl, sl), jnp.bool_, s)
    add("flash_attention", "masked_fwd",
        lambda q, k, v, m: flash_attention(q, k, v, mask=m),
        qs, qs, qs, mask)
    add("flash_attention", "dropout_fwd",
        lambda q, k, v: flash_attention(q, k, v, True, dropout_p=0.1,
                                        dropout_seed=7), qs, qs, qs)
    rq = _struct((b, h, 1993, d), jnp.bfloat16, s)
    rk = _struct((b, h, 2017, d), jnp.bfloat16, s)
    add("flash_attention", "ragged_fwd",
        lambda q, k, v: flash_attention(q, k, v, True), rq, rk, rk)

    # ---- one-sided remote DMA over the 4-device topology mesh ----------
    # shard_map + make_async_remote_copy compiled by Mosaic for a REAL
    # multi-chip ring — the multi-device path has only ever run in
    # interpret mode on the CPU mesh
    from apex_tpu.ops.pallas.remote_copy import (halo_exchange_rdma,
                                                 peer_shift)
    ns = NamedSharding(mesh, P("x"))
    xr = _struct((64, 2048), jnp.float32, ns)

    def rdma_body(x):
        y = peer_shift(x, "x", 1)
        lo, hi = halo_exchange_rdma(x, "x", 2)
        return y, lo, hi

    add("remote_copy", "ring4_shift_halo",
        lambda x: shard_map(rdma_body, mesh=mesh, in_specs=P("x"),
                                out_specs=(P("x"), P("x"), P("x")),
                                check_vma=False)(x), xr)

    # pool-backed landing buffers: remote puts must alias into donated
    # storage (input_output_aliases through shard_map → Mosaic)
    from apex_tpu.ops.pallas.remote_copy import halo_buf_rows

    per_dev_rows = 64 // mesh.shape["x"]
    br = halo_buf_rows(per_dev_rows, 2, jnp.float32)
    buf = _struct((br * mesh.shape["x"], 2048), jnp.float32, ns)

    def rdma_pool_body(x, lo_in, hi_in):
        return halo_exchange_rdma(x, "x", 2, bufs=(lo_in, hi_in))

    add("remote_copy", "ring4_halo_pool_bufs",
        lambda x, lo, hi: shard_map(
            rdma_pool_body, mesh=mesh, in_specs=(P("x"), P("x"), P("x")),
            out_specs=(P("x"), P("x")), check_vma=False)(x, lo, hi),
        xr, buf, buf)

    # ---- beyond chipcheck: ring attention over the topology mesh -------
    from apex_tpu.parallel.ring_attention import ring_attention

    nring = mesh.shape["x"]
    qr = _struct((1, 8, nring * 1024, 64), jnp.bfloat16,
                 NamedSharding(mesh, P(None, None, "x", None)))
    add("ring_attention", f"collective_{nring}dev",
        lambda q, k, v: shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="x"),
            mesh=mesh,
            in_specs=P(None, None, "x", None),
            out_specs=P(None, None, "x", None),
            check_vma=False)(q, k, v),
        qr, qr, qr)
    return cases


def main():
    t0 = time.time()
    topo = get_topology()
    devs = topo.devices
    dev_sharding = SingleDeviceSharding(devs[0])
    nmesh = min(4, len(devs))
    mesh = Mesh(np.array(devs[:nmesh]).reshape(nmesh), ("x",))
    result = {"topology": os.environ.get("MOSAIC_AOT_TOPOLOGY", "v5e:2x2"),
              "device_kind": getattr(devs[0], "device_kind", "?"),
              "n_devices": len(devs),
              "jax": jax.__version__,
              "captured": time.strftime("%Y-%m-%dT%H:%M:%S"),
              "kernels": {}}

    cases = build_cases(dev_sharding, mesh)
    ok_all = True
    for kernel, tag, fn, args in cases:
        rec = result["kernels"].setdefault(kernel,
                                           {"compiled": True, "tags": {}})
        t1 = time.time()
        try:
            compiled = jax.jit(fn).lower(*args).compile()
            entry = {"ok": True}
            try:  # best-effort: analysis failure is not a compile failure
                mem = compiled.memory_analysis()
                entry["hbm_args_bytes"] = int(mem.argument_size_in_bytes)
                entry["hbm_tmp_bytes"] = int(mem.temp_size_in_bytes)
            except Exception:
                pass
        except Exception as e:
            entry = {"ok": False,
                     "error": f"{type(e).__name__}: {str(e)[:1500]}"}
            rec["compiled"] = False
            ok_all = False
        entry["wall_s"] = round(time.time() - t1, 1)
        rec["tags"][tag] = entry
        print(f"[mosaic_aot] {kernel}:{tag} "
              f"{'OK' if entry['ok'] else 'FAIL ' + entry.get('error', '')}"
              f" ({entry['wall_s']}s)", file=sys.stderr, flush=True)
        # incremental write: a crash mid-run still leaves evidence
        result["ok"] = False
        result["wall_s"] = round(time.time() - t0, 1)
        atomic_write_json(OUT_PATH, result)

    result["ok"] = ok_all
    result["wall_s"] = round(time.time() - t0, 1)
    atomic_write_json(OUT_PATH, result)
    n_tags = sum(len(r["tags"]) for r in result["kernels"].values())
    print(json.dumps({"ok": ok_all, "kernels": len(result["kernels"]),
                      "tags": n_tags, "wall_s": result["wall_s"]}))
    sys.exit(0 if ok_all else 2)


if __name__ == "__main__":
    main()
