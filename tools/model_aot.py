"""Deviceless Mosaic/XLA AOT compile of the FULL bench model steps.

Companion to tools/mosaic_aot.py (kernel zoo): compiles the exact
BASELINE configs 2/4/5 bench programs — ResNet-50 b128@224 train step,
BERT-large b32 s128 LAMB train step, GPT-2 1.5B b4 s512 bf16 forward —
against a compile-only v5e client built from the baked-in libtpu. Proves
the headline bench programs compile for TPU (layout, VMEM, HBM fit)
before any chip time is spent, and records XLA's own cost model
(flops/bytes per step) plus the roofline-implied step-time bounds as
committed evidence (MODEL_AOT.json).

HBM-fit check: ``memory_analysis`` argument+temp+output bytes must fit
the 16 GB v5e HBM, the compile-time analog of the OOM the bench would
hit live.
"""

from __future__ import annotations

import json
import os
import sys
import time

# shared compile-only scaffolding (env + CPU pin + cache) — must import
# before jax backend use
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _aot_common import (ROOT, atomic_write_json,  # noqa: E402
                         get_topology)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import SingleDeviceSharding  # noqa: E402

OUT_PATH = os.environ.get("MODEL_AOT_OUT",
                          os.path.join(ROOT, "MODEL_AOT.json"))
HBM_BYTES = 16e9  # v5e


def _structs(tree, s):
    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s), tree)


def case_resnet50(s):
    """BASELINE config 2: the exact q050/bench ResNet-50 train step."""
    from apex_tpu.models.resnet import ResNet50
    from apex_tpu.optimizers.functional import adam_update

    model, batch, hw, ncls = ResNet50(), 128, 224, 1000
    x = jax.ShapeDtypeStruct((batch, hw, hw, 3), jnp.bfloat16, sharding=s)
    y = jax.ShapeDtypeStruct((batch,), jnp.int32, sharding=s)
    vs = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((batch, hw, hw, 3), jnp.bfloat16)),
        jax.random.PRNGKey(0))
    params, bstats = _structs(vs["params"], s), _structs(vs["batch_stats"], s)
    mom = jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(p.shape, jnp.float32, sharding=s),
        params)

    def step(state, x, y):
        p, m, v, bs = state

        def loss_fn(p):
            logits, upd = model.apply({"params": p, "batch_stats": bs}, x,
                                      mutable=["batch_stats"])
            onehot = jax.nn.one_hot(y, logits.shape[-1])
            loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot,
                                     axis=-1))
            return loss, upd["batch_stats"]

        (_, bs2), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
        p, m, v = adam_update(p, grads, m, v, step=1, lr=1e-3,
                              weight_decay=1e-4)
        return (p, m, v, bs2)

    return step, ((params, mom, mom, bstats), x, y)


def case_bert_lamb(s):
    """BASELINE config 4: BERT-large b32 s128 LAMB train step."""
    from apex_tpu.models.bert import Bert, BertConfig
    from apex_tpu.optimizers.functional import lamb_update

    cfg, batch, seq = BertConfig.large(), 32, 128
    model = Bert(cfg)
    tokens = jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=s)
    vs = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((batch, seq), jnp.int32)),
        jax.random.PRNGKey(0))
    params = _structs(vs["params"], s)
    mom = jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(p.shape, jnp.float32, sharding=s),
        params)

    def step(state, tokens, labels):
        p, m, v = state

        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            onehot = jax.nn.one_hot(labels, logits.shape[-1])
            return -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits.astype(jnp.float32)) * onehot,
                axis=-1))

        _, grads = jax.value_and_grad(loss_fn)(p)
        p, m, v, _g = lamb_update(p, grads, m, v, step=1, lr=1e-3,
                                  weight_decay=0.01)
        return (p, m, v)

    return step, ((params, mom, mom), tokens, tokens)


def case_gpt2_fwd(s):
    """BASELINE config 5: GPT-2 1.5B bf16 forward, b4 s512."""
    from apex_tpu.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config.xl()
    cfg = type(cfg)(**{**cfg.__dict__, "n_positions": 512})
    batch, seq = 4, 512
    model = GPT2(cfg)
    tokens = jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=s)
    vs = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((batch, seq), jnp.int32)),
        jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(
            l.shape, jnp.bfloat16 if l.dtype == jnp.float32 else l.dtype,
            sharding=s), vs)

    def step(params, tokens):
        return jnp.sum(model.apply(params, tokens).astype(jnp.float32))

    return step, (params, tokens)


def case_lm_head_fused(s):
    """Chunked-vocab fused linear+CE at long-batch LM-head scale
    (N=16384 ≈ b8·s2048, H=1600, V=50257), fwd+bwd — the
    full-logits-free training head. The dense baseline below materializes
    the (16384, 50257) logits (bf16 after XLA fuses the fp32 cast,
    ~1.65 GB of temp) where this case streams vocab chunks."""
    from apex_tpu.transformer import linear_cross_entropy

    n, h, v = 16384, 1600, 50257
    hd = jax.ShapeDtypeStruct((n, h), jnp.bfloat16, sharding=s)
    w = jax.ShapeDtypeStruct((h, v), jnp.bfloat16, sharding=s)
    lb = jax.ShapeDtypeStruct((n,), jnp.int32, sharding=s)

    def step(hd, w, lb):
        return jax.grad(
            lambda hd, w: jnp.mean(linear_cross_entropy(hd, w, lb)),
            argnums=(0, 1))(hd, w)

    return step, (hd, w, lb)


def case_lm_head_dense(s):
    """Same computation via materialized logits + contrib.xentropy — the
    memory baseline the fused head exists to beat."""
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss

    n, h, v = 16384, 1600, 50257
    hd = jax.ShapeDtypeStruct((n, h), jnp.bfloat16, sharding=s)
    w = jax.ShapeDtypeStruct((h, v), jnp.bfloat16, sharding=s)
    lb = jax.ShapeDtypeStruct((n,), jnp.int32, sharding=s)

    def step(hd, w, lb):
        def loss(hd, w):
            logits = (hd @ w).astype(jnp.float32)
            return jnp.mean(softmax_cross_entropy_loss(logits, lb))

        return jax.grad(loss, argnums=(0, 1))(hd, w)

    return step, (hd, w, lb)


CASES = [("resnet50_b128_train", case_resnet50),
         ("bert_large_b32_lamb_train", case_bert_lamb),
         ("gpt2_xl_b4_s512_fwd", case_gpt2_fwd),
         ("lm_head_fused_linear_ce", case_lm_head_fused),
         ("lm_head_dense_baseline", case_lm_head_dense)]

# honesty notes stamped into the artifact: XLA cost_analysis counts a
# lax.scan (while-loop) body ONCE, so scan-based cases' flops/t_mxu_ms
# understate true per-step cost by the trip count
NOTES = {
    "lm_head_fused_linear_ce":
        "cost_analysis counts the vocab scan body once: true per-step "
        "flops ~= reported x7 trips (~1.2e13, ~30 ms MXU) - by design "
        "the fused head trades MXU flops (logits rematerialized in bwd) "
        "for HBM capacity; hbm_total_bytes is the honest comparison "
        "field vs lm_head_dense_baseline",
}


def main():
    t0 = time.time()
    topo = get_topology()
    s = SingleDeviceSharding(topo.devices[0])
    chip = {"tflops": 394.0, "hbm_gbps": 819.0}  # v5e bf16 peaks
    result = {"device_kind": getattr(topo.devices[0], "device_kind", "?"),
              "jax": jax.__version__,
              "captured": time.strftime("%Y-%m-%dT%H:%M:%S"),
              "models": {}}
    ok_all = True
    for name, make in CASES:
        t1 = time.time()
        try:
            fn, args = make(s)
            compiled = jax.jit(fn).lower(*args).compile()
            entry = {"ok": True}
            try:
                ca = compiled.cost_analysis()
                ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
                fl = float(ca.get("flops", 0.0))
                by = float(ca.get("bytes accessed", 0.0))
                entry["flops_per_step"] = fl
                entry["bytes_accessed"] = by
                entry["t_mxu_ms"] = round(fl / (chip["tflops"] * 1e12) * 1e3,
                                          2)
                # upper bound only — operand bytes include VMEM reuse (see
                # utils/prof.roofline docstring)
                entry["t_hbm_upper_ms"] = round(
                    by / (chip["hbm_gbps"] * 1e9) * 1e3, 2)
            except Exception as e:
                entry["cost_analysis_error"] = str(e)[:200]
            try:
                mem = compiled.memory_analysis()
                total = (mem.argument_size_in_bytes + mem.temp_size_in_bytes
                         + mem.output_size_in_bytes)
                entry["hbm_total_bytes"] = int(total)
                entry["fits_hbm"] = bool(total < HBM_BYTES)
                if not entry["fits_hbm"]:
                    entry["ok"] = False
            except Exception as e:
                entry["memory_analysis_error"] = str(e)[:200]
        except Exception as e:
            entry = {"ok": False,
                     "error": f"{type(e).__name__}: {str(e)[:1500]}"}
        entry["wall_s"] = round(time.time() - t1, 1)
        if name in NOTES:
            entry["cost_note"] = NOTES[name]
        ok_all = ok_all and entry["ok"]
        result["models"][name] = entry
        print(f"[model_aot] {name} "
              f"{'OK' if entry['ok'] else 'FAIL ' + entry.get('error', '')}"
              f" ({entry['wall_s']}s)", file=sys.stderr, flush=True)
        result["ok"] = False
        result["wall_s"] = round(time.time() - t0, 1)
        atomic_write_json(OUT_PATH, result)
    result["ok"] = ok_all
    result["wall_s"] = round(time.time() - t0, 1)
    atomic_write_json(OUT_PATH, result)
    print(json.dumps({"ok": ok_all, "wall_s": result["wall_s"]}))
    sys.exit(0 if ok_all else 2)


if __name__ == "__main__":
    main()
