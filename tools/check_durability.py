#!/usr/bin/env python
"""Static durability check — thin shim over apexlint rule APX004.

The checker itself now lives in the reusable lint framework
(``tools/apexlint/rules/durability.py``, rule **APX004**) together with
the other repo invariants; this script keeps the original CLI contract
for existing callers and docs:

- ``python tools/check_durability.py`` from the repo root,
- exit 0 clean / 1 on violations (listed one per line on stderr),
- ``_check_file(path)`` stays importable for tests.

Prefer the full linter: ``apex-tpu-lint`` or
``python -m tools.apexlint`` (``--rules APX004`` for just this rule).
See docs/static-analysis.md.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:  # script execution: make tools.apexlint importable
    sys.path.insert(0, ROOT)

from tools.apexlint.rules.durability import check_source  # noqa: E402


def _check_file(path: str) -> List[Tuple[int, str]]:
    """``[(lineno, message)]`` durability findings for one file."""
    with open(path, encoding="utf-8") as f:
        return check_source(path, f.read())


def main() -> int:
    from tools.apexlint.core import run_lint

    active, _suppressed, _ctx = run_lint(
        root=ROOT, paths=[os.path.join(ROOT, "apex_tpu")],
        only=["APX004"])
    if active:
        # the original tool's output shape: header + one violation per
        # line on STDERR (log pipelines grep that stream)
        print("durability check FAILED:", file=sys.stderr)
        for v in active:
            print(f"  {v.path}:{v.line}: {v.message}", file=sys.stderr)
        return 1
    print("durability check OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
