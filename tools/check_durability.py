#!/usr/bin/env python
"""Static durability check: no non-atomic writes on checkpoint paths.

A checkpoint written with a bare ``open(path, "w")`` / ``np.savez(path)``
can be torn by a crash and then loaded (or choked on) at restore — the
exact failure class ``apex_tpu.resilience`` exists to close. This check
greps the package AST for write calls in checkpoint-flavored code and
fails unless the enclosing function shows the atomic-commit discipline:
stage to ``.tmp`` + publish with ``os.replace``, or route through the
``Filesystem.write_bytes`` seam (whose sole implementation follows it),
or write only to an in-memory buffer.

Scope (kept deliberately narrow to stay false-positive-free):
- files whose path contains ``checkpoint``, and
- functions whose name contains save/checkpoint/ckpt/manifest anywhere in
  ``apex_tpu/``.

Exit status: 0 clean, 1 on violations (listed one per line). Run as
``python tools/check_durability.py`` from the repo root; the tier-1 suite
runs it (tests/test_resilience.py) so new violations fail CI.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(ROOT, "apex_tpu")

CKPT_NAME_HINTS = ("save", "checkpoint", "ckpt", "manifest")
WRITE_MODES = ("w", "wb", "w+", "wb+", "x", "xb")
# evidence of the atomic-commit discipline inside a function's source
SAFE_MARKERS = (".tmp", "os.replace")
# writes through these are safe by construction (in-memory, or the fs seam)
SAFE_CALL_HINTS = ("BytesIO", "write_bytes", "StringIO")
ALLOWED_FUNCS = {"write_bytes"}  # the seam's own implementation


def _is_write_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in ("save", "savez",
                                                   "savez_compressed"):
        root = f.value
        if isinstance(root, ast.Name) and root.id in ("np", "numpy"):
            return True
    if isinstance(f, ast.Name) and f.id == "open":
        mode = None
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        return isinstance(mode, str) and mode in WRITE_MODES
    return False


def _check_file(path: str) -> List[Tuple[int, str]]:
    src = open(path).read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [(e.lineno or 0, f"unparseable: {e.msg}")]
    ckpt_file = "checkpoint" in os.path.basename(path).lower()
    lines = src.splitlines()
    violations: List[Tuple[int, str]] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: List[ast.AST] = []

        def visit_FunctionDef(self, node):
            self.stack.append(node)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if _is_write_call(node):
                fn = self.stack[-1] if self.stack else None
                name = fn.name if fn is not None else "<module>"
                in_scope = ckpt_file or any(
                    h in name.lower() for h in CKPT_NAME_HINTS)
                if in_scope and name not in ALLOWED_FUNCS:
                    seg = ("\n".join(
                        lines[fn.lineno - 1:fn.end_lineno])
                        if fn is not None else src)
                    safe = (all(m in seg for m in SAFE_MARKERS)
                            or any(h in seg for h in SAFE_CALL_HINTS))
                    if not safe:
                        violations.append((
                            node.lineno,
                            f"{name}: non-atomic write on a checkpoint "
                            f"path (want .tmp + os.replace, or the "
                            f"Filesystem.write_bytes seam)"))
            self.generic_visit(node)

    V().visit(tree)
    return violations


def main() -> int:
    bad = []
    for dirpath, _dirnames, filenames in os.walk(PACKAGE):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            for lineno, msg in _check_file(path):
                bad.append(f"{os.path.relpath(path, ROOT)}:{lineno}: {msg}")
    if bad:
        print("durability check FAILED:", file=sys.stderr)
        for b in bad:
            print("  " + b, file=sys.stderr)
        return 1
    print("durability check OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
