#!/usr/bin/env python
"""Inspect + verify a committed checkpoint directory, without jax.

Dumps the newest (or ``--step N``) committed step's manifest as JSON:
the step number, the ``layout`` block (which (dp world, grad_shards, tp)
topology wrote it, and whether the files are dense or sharded), and the
per-leaf shapes, dtypes, and digests — then re-reads every referenced
blob file and verifies its byte length, crc32, and (when stamped)
blake2b-128 against the manifest.

Usage::

    python tools/ckpt_inspect.py /ckpt                 # newest step
    python tools/ckpt_inspect.py /ckpt --step 8        # a specific step
    python tools/ckpt_inspect.py /ckpt --no-verify     # manifest only

Exit status: 0 verified (or listed with ``--no-verify``), 2 on a torn or
unparseable manifest, a missing/short blob, or any digest mismatch — the
same refuse-loudly contract the restore path enforces, available from an
operator box that has no jax (or whose jax must not be imported by a
forensic tool). This tool is **standalone stdlib**: digests cover the
serialized blob bytes, so nothing here parses npy.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import re
import sys
import zlib
from typing import Any, Dict, List, Optional

MANIFEST_NAME = "manifest.json"
_STEP_RE = re.compile(r"^step_(\d{8})$")


class InspectError(Exception):
    """A torn manifest, a missing blob, or a digest mismatch."""


def committed_steps(directory: str) -> List[int]:
    try:
        names = os.listdir(directory)
    except OSError as e:
        raise InspectError(f"{directory}: {e}") from e
    return sorted(int(m.group(1)) for n in names
                  if (m := _STEP_RE.match(n)))


def _read_manifest(path: str) -> Dict[str, Any]:
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        raise InspectError(f"{path}: missing {MANIFEST_NAME} (torn or "
                           f"uncommitted step)")
    try:
        with open(mpath, "rb") as f:
            manifest = json.loads(f.read())
    except (ValueError, OSError) as e:
        raise InspectError(f"{mpath}: torn manifest ({e})")
    if not isinstance(manifest, dict) or "leaves" not in manifest:
        raise InspectError(f"{mpath}: torn manifest (no leaf table)")
    leaves = manifest["leaves"]
    if not isinstance(leaves, list) or \
            len(leaves) != manifest.get("num_leaves"):
        raise InspectError(f"{mpath}: torn manifest (leaf table "
                           f"truncated: {len(leaves)} of "
                           f"{manifest.get('num_leaves')})")
    return manifest


def _verify_blob(path: str, ent: Dict[str, Any]) -> None:
    fpath = os.path.join(path, ent["file"])
    if not os.path.exists(fpath):
        raise InspectError(f"{fpath}: missing blob file")
    with open(fpath, "rb") as f:
        data = f.read()
    if len(data) != ent.get("nbytes"):
        raise InspectError(f"{fpath}: {len(data)} bytes, manifest says "
                           f"{ent.get('nbytes')}")
    if zlib.crc32(data) != ent.get("crc32"):
        raise InspectError(f"{fpath}: crc32 mismatch")
    want = ent.get("blake2b")
    if want is not None and hashlib.blake2b(
            data, digest_size=16).hexdigest() != want:
        raise InspectError(f"{fpath}: blake2b digest mismatch")


def inspect_step(directory: str, step: int,
                 verify: bool = True) -> Dict[str, Any]:
    """The inspection record for one committed step (raises
    :class:`InspectError` on anything the restore path would refuse)."""
    path = os.path.join(directory, f"step_{step:08d}")
    manifest = _read_manifest(path)
    layout = manifest.get("layout")
    # the layout block is polymorphic: absent (legacy dense), the legacy
    # "sharded" string, or a dict with a "storage" discriminator
    if isinstance(layout, dict):
        storage = layout.get("storage", "dense")
    elif isinstance(layout, str):
        storage, layout = layout, None
    else:
        storage = "dense"
    leaves_out = []
    checked = 0
    for i, leaf in enumerate(manifest["leaves"]):
        if "shards" in leaf:  # sharded manifest: per-region entries
            ents = leaf["shards"]
            rec: Dict[str, Any] = {
                "leaf": i, "shape": leaf.get("shape"),
                "dtype": leaf.get("dtype"), "shards": len(ents),
                "blake2b": [e.get("blake2b") for e in ents],
            }
        else:  # dense manifest: the leaf IS one blob entry
            ents = [leaf]
            rec = {"leaf": i, "shape": leaf.get("shape"),
                   "dtype": leaf.get("dtype"), "file": leaf.get("file"),
                   "blake2b": leaf.get("blake2b")}
        if verify:
            for ent in ents:
                _verify_blob(path, ent)
                checked += 1
        leaves_out.append(rec)
    return {"step": step, "path": path, "storage": storage,
            "layout": layout, "num_leaves": len(leaves_out),
            "blobs_verified": checked if verify else None,
            "leaves": leaves_out}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="dump + digest-verify a committed checkpoint's "
                    "manifest (step, topology layout block, per-leaf "
                    "shapes/digests) without importing jax")
    ap.add_argument("directory", help="the checkpoint directory")
    ap.add_argument("--step", type=int, default=None,
                    help="inspect this committed step (default: newest)")
    ap.add_argument("--no-verify", action="store_true",
                    help="dump the manifest without re-reading blobs")
    args = ap.parse_args(argv)

    try:
        steps = committed_steps(args.directory)
        if not steps:
            raise InspectError(f"{args.directory}: no committed steps")
        step = args.step if args.step is not None else steps[-1]
        if step not in steps:
            raise InspectError(
                f"step {step} is not committed (have: {steps})")
        record = inspect_step(args.directory, step,
                              verify=not args.no_verify)
    except InspectError as e:
        print(f"ckpt_inspect: {e}", file=sys.stderr)
        return 2
    record["all_steps"] = steps
    print(json.dumps(record, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
