"""Unbounded background chip worker (VERDICT r3 item 1).

ONE long-lived process that initializes the JAX backend ONCE (blocking as
long as the relay needs — never under `timeout`, never killed: SIGTERM-ing
a TPU-holding process wedges the axon relay for hours) and then executes
queued job scripts in-process, sequentially, each writing its own artifact
incrementally. Split of acquisition from reporting: bench.py only READS the
artifacts this worker writes, so the driver's bounded bench window can
never rc=124 again.

Usage (from the repo root):

    nohup python -u tools/chip_worker.py >> tools/chipq/worker.log 2>&1 &

Queue protocol:
- jobs are ``tools/chipq/q*.py``, executed in sorted order via runpy;
- a finished job leaves ``tools/chipq/done/<name>.json`` ({ok, wall_s, ...});
  delete the marker to re-run a job after editing it;
- ``apex_tpu``/``bench``/``chipcheck`` modules are purged from sys.modules
  before every job so edits made after worker launch take effect;
- ``tools/chipq/STOP`` (or CHIPQ_IDLE_EXIT_S seconds with an empty queue)
  makes the worker exit cleanly, RELEASING the chip claim so the driver's
  end-of-round bench/dryrun can reach the relay;
- ``tools/chipq/status.json`` carries {pid, phase, backend, job} for
  outside observers (bench.py checks it before daring to probe).
"""

from __future__ import annotations

import json
import os
import runpy
import sys
import time
import traceback

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# CHIPQ_DIR override lets tests drive the worker end-to-end against a
# throwaway queue without touching the real one
QDIR = os.environ.get("CHIPQ_DIR", os.path.join(ROOT, "tools", "chipq"))
DONE = os.path.join(QDIR, "done")
FAILED = os.path.join(QDIR, "failed")
STATUS = os.path.join(QDIR, "status.json")

if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def _fail_count(job: str) -> int:
    try:
        return sum(1 for f in os.listdir(FAILED)
                   if f.startswith(job + "."))
    except FileNotFoundError:
        return 0


def _last_fail_age_s(job: str) -> float:
    """Seconds since the newest failure marker for ``job`` (inf if none)."""
    try:
        ts = [os.path.getmtime(os.path.join(FAILED, f))
              for f in os.listdir(FAILED) if f.startswith(job + ".")]
    except FileNotFoundError:
        return float("inf")
    return time.time() - max(ts) if ts else float("inf")


def job_runnable(job: str, retry_backoff_s: float) -> bool:
    """done marker ⇒ finished OK; failed markers are retried up to 3 times
    (a transient relay error must not permanently block a job, a
    deterministic failure must not loop forever), with a backoff after each
    failure so a transient outage can't burn all 3 attempts within seconds
    (ADVICE r4) — later jobs run while a freshly-failed one cools down."""
    if os.path.exists(os.path.join(DONE, job + ".json")):
        return False
    n = _fail_count(job)
    if n >= 3:
        return False
    return n == 0 or _last_fail_age_s(job) >= retry_backoff_s


def log(msg: str) -> None:
    print(f"[worker {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def write_status(**kw) -> None:
    from bench import atomic_write_json

    kw.setdefault("pid", os.getpid())
    kw["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    atomic_write_json(STATUS, kw)


def purge_repo_modules() -> None:
    """Drop repo-owned modules so each job re-imports current source."""
    for name in list(sys.modules):
        head = name.split(".")[0]
        if head in ("apex_tpu", "bench", "chipcheck", "tune_flash",
                    "bench_cli", "__graft_entry__"):
            del sys.modules[name]


def main() -> None:
    os.makedirs(DONE, exist_ok=True)
    os.makedirs(FAILED, exist_ok=True)
    attempt = int(os.environ.get("CHIPQ_ATTEMPT", "1"))
    write_status(phase="importing_jax", attempt=attempt)
    t0 = time.time()
    log(f"initializing JAX backend, attempt {attempt} (may block on the "
        "relay; that is fine)")
    try:
        import jax  # noqa: F401  — the long pole; never under a timeout

        try:  # persistent compile cache shortens re-measurement jobs
            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(ROOT, ".jax_cache"))
        except Exception:
            pass
        backend = jax.default_backend()
    except Exception as e:
        # init RAISED (observed: UNAVAILABLE after ~2h on a wedged relay)
        # rather than hanging. No claim is held after a failed init, and
        # xla_bridge caches the failure — so retry with a FRESH interpreter
        # via exec, forever. A clean raise is not the kill-mid-claim wedge
        # case; re-exec is safe.
        log(f"backend init failed ({type(e).__name__}: {e}); retrying in "
            "120s via re-exec")
        write_status(phase="init_retry_sleep", attempt=attempt,
                     error=f"{type(e).__name__}: {e}"[:300])
        time.sleep(120)
        env = dict(os.environ)
        env["CHIPQ_ATTEMPT"] = str(attempt + 1)
        os.execve(sys.executable, [sys.executable, "-u",
                                   os.path.abspath(__file__)], env)
    acquire_s = round(time.time() - t0, 1)
    if backend != "tpu" and os.environ.get("CHIPQ_ALLOW_CPU") != "1":
        # a CPU backend means the relay quietly handed us nothing — the
        # queue jobs are chip-acceptance jobs; burning them in interpret
        # mode helps no one. Retry for the TPU like an init failure.
        log(f"backend came up as {backend!r}, not tpu; retrying in 120s")
        write_status(phase="init_retry_sleep", attempt=attempt,
                     error=f"backend={backend}")
        time.sleep(120)
        env = dict(os.environ)
        env["CHIPQ_ATTEMPT"] = str(attempt + 1)
        os.execve(sys.executable, [sys.executable, "-u",
                                   os.path.abspath(__file__)], env)
    write_status(phase="ready", backend=backend, acquire_s=acquire_s)
    log(f"backend={backend} acquired in {acquire_s}s; "
        f"devices={jax.devices()}")

    idle_exit_s = float(os.environ.get("CHIPQ_IDLE_EXIT_S", "1800"))
    last_work = time.time()
    while True:
        if os.path.exists(os.path.join(QDIR, "STOP")):
            log("STOP file present — exiting cleanly")
            break
        jobs = sorted(f for f in os.listdir(QDIR)
                      if f.startswith("q") and f.endswith(".py"))

        retry_backoff_s = float(os.environ.get("CHIPQ_RETRY_BACKOFF_S",
                                               "600"))
        pending = [j for j in jobs if job_runnable(j, retry_backoff_s)]
        if not pending:
            cooling = [j for j in jobs
                       if not os.path.exists(os.path.join(DONE, j + ".json"))
                       and 0 < _fail_count(j) < 3]
            if cooling:  # deferred retries exist: don't start the idle clock
                last_work = time.time()
            if time.time() - last_work > idle_exit_s:
                log(f"queue idle for {idle_exit_s:.0f}s — exiting to "
                    "release the chip claim")
                break
            n_done = sum(
                1 for j in jobs
                if os.path.exists(os.path.join(DONE, j + ".json")))
            write_status(phase="idle", backend=backend,
                         done=n_done, pending=0,
                         cooling=len(cooling))
            time.sleep(15)
            continue
        name = pending[0]
        write_status(phase="running", backend=backend, job=name)
        log(f"running {name}")
        rec = {"job": name, "backend": backend,
               "started": time.strftime("%Y-%m-%dT%H:%M:%S")}
        t0 = time.time()
        try:
            purge_repo_modules()
            runpy.run_path(os.path.join(QDIR, name), run_name="chipq_job")
            rec["ok"] = True
        except SystemExit as e:
            rec["ok"] = e.code in (0, None)
            rec["exit"] = e.code
        except MemoryError:
            rec["ok"] = False
            rec["error"] = "MemoryError"
        except Exception:
            rec["ok"] = False
            rec["error"] = traceback.format_exc()[-4000:]
        rec["wall_s"] = round(time.time() - t0, 1)
        if rec["ok"]:
            marker = os.path.join(DONE, name + ".json")
        else:
            marker = os.path.join(FAILED,
                                  f"{name}.{_fail_count(name) + 1}.json")
        with open(marker, "w") as f:
            json.dump(rec, f, indent=1)
        log(f"done {name} ok={rec['ok']} wall={rec['wall_s']}s"
            + (f" error={rec.get('error', '')[-300:]}" if not rec["ok"]
               else ""))
        last_work = time.time()
    write_status(phase="exited", backend=backend)


if __name__ == "__main__":
    main()
