"""Unbounded background chip worker (VERDICT r3 item 1).

ONE long-lived process that initializes the JAX backend ONCE (blocking as
long as the relay needs — never under `timeout`, never killed: SIGTERM-ing
a TPU-holding process wedges the axon relay for hours) and then executes
queued job scripts in-process, sequentially, each writing its own artifact
incrementally. Split of acquisition from reporting: bench.py only READS the
artifacts this worker writes, so the driver's bounded bench window can
never rc=124 again.

Usage (from the repo root):

    nohup python -u tools/chip_worker.py >> tools/chipq/worker.log 2>&1 &

Queue protocol:
- jobs are ``tools/chipq/q*.py``, executed in sorted order via runpy;
- a finished job leaves ``tools/chipq/done/<name>.json`` ({ok, wall_s, ...});
  delete the marker to re-run a job after editing it;
- ``apex_tpu``/``bench``/``chipcheck`` modules are purged from sys.modules
  before every job so edits made after worker launch take effect;
- ``tools/chipq/STOP`` (or CHIPQ_IDLE_EXIT_S seconds with an empty queue)
  makes the worker exit cleanly, RELEASING the chip claim so the driver's
  end-of-round bench/dryrun can reach the relay;
- ``tools/chipq/status.json`` carries {pid, phase, backend, job} for
  outside observers (bench.py checks it before daring to probe).
"""

from __future__ import annotations

import json
import os
import runpy
import sys
import time
import traceback

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
QDIR = os.path.join(ROOT, "tools", "chipq")
DONE = os.path.join(QDIR, "done")
STATUS = os.path.join(QDIR, "status.json")

if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def log(msg: str) -> None:
    print(f"[worker {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def write_status(**kw) -> None:
    kw.setdefault("pid", os.getpid())
    kw["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    tmp = STATUS + ".tmp"
    with open(tmp, "w") as f:
        json.dump(kw, f, indent=1)
    os.replace(tmp, STATUS)


def purge_repo_modules() -> None:
    """Drop repo-owned modules so each job re-imports current source."""
    for name in list(sys.modules):
        head = name.split(".")[0]
        if head in ("apex_tpu", "bench", "chipcheck", "tune_flash",
                    "bench_cli", "__graft_entry__"):
            del sys.modules[name]


def main() -> None:
    os.makedirs(DONE, exist_ok=True)
    write_status(phase="importing_jax")
    t0 = time.time()
    log("initializing JAX backend (may block on the relay; that is fine)")
    import jax  # noqa: F401  — the long pole; never under a timeout

    try:  # persistent compile cache shortens re-measurement jobs
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(ROOT, ".jax_cache"))
    except Exception:
        pass
    backend = jax.default_backend()
    acquire_s = round(time.time() - t0, 1)
    write_status(phase="ready", backend=backend, acquire_s=acquire_s)
    log(f"backend={backend} acquired in {acquire_s}s; "
        f"devices={jax.devices()}")

    idle_exit_s = float(os.environ.get("CHIPQ_IDLE_EXIT_S", "1800"))
    last_work = time.time()
    while True:
        if os.path.exists(os.path.join(QDIR, "STOP")):
            log("STOP file present — exiting cleanly")
            break
        jobs = sorted(f for f in os.listdir(QDIR)
                      if f.startswith("q") and f.endswith(".py"))
        pending = [j for j in jobs
                   if not os.path.exists(os.path.join(DONE, j + ".json"))]
        if not pending:
            if time.time() - last_work > idle_exit_s:
                log(f"queue idle for {idle_exit_s:.0f}s — exiting to "
                    "release the chip claim")
                break
            write_status(phase="idle", backend=backend,
                         done=len(jobs), pending=0)
            time.sleep(15)
            continue
        name = pending[0]
        write_status(phase="running", backend=backend, job=name)
        log(f"running {name}")
        rec = {"job": name, "backend": backend,
               "started": time.strftime("%Y-%m-%dT%H:%M:%S")}
        t0 = time.time()
        try:
            purge_repo_modules()
            runpy.run_path(os.path.join(QDIR, name), run_name="chipq_job")
            rec["ok"] = True
        except SystemExit as e:
            rec["ok"] = e.code in (0, None)
            rec["exit"] = e.code
        except MemoryError:
            rec["ok"] = False
            rec["error"] = "MemoryError"
        except Exception:
            rec["ok"] = False
            rec["error"] = traceback.format_exc()[-4000:]
        rec["wall_s"] = round(time.time() - t0, 1)
        with open(os.path.join(DONE, name + ".json"), "w") as f:
            json.dump(rec, f, indent=1)
        log(f"done {name} ok={rec['ok']} wall={rec['wall_s']}s"
            + (f" error={rec.get('error', '')[-300:]}" if not rec["ok"]
               else ""))
        last_work = time.time()
    write_status(phase="exited", backend=backend)


if __name__ == "__main__":
    main()
