"""``python -m tools.apexlint`` — see cli.py for the contract."""

import sys

from .cli import main

sys.exit(main())
