"""Violation reporters: human text and machine JSON.

The JSON shape is the contract CI consumers read::

    {"ok": bool, "violations": [...], "suppressed": [...],
     "counts": {"APX001": 2, ...}, "suppressed_counts": {...},
     "files_scanned": N, "rules": {"APX001": "summary", ...}}

``suppressed`` entries carry their mandatory justification text, so an
audit of every opt-out in the repo is one ``jq`` away.
"""

from __future__ import annotations

import json
from typing import Dict, List, TextIO

from .core import LintContext, Rule, Violation


def report_text(active: List[Violation], suppressed: List[Violation],
                ctx: LintContext, stream: TextIO) -> None:
    for v in active:
        print(v.format(), file=stream)
    tail = (f"apexlint: {len(active)} violation(s), "
            f"{len(suppressed)} suppressed, "
            f"{len(ctx.files)} file(s) scanned")
    print(tail, file=stream)


def _counts(violations: List[Violation]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for v in violations:
        out[v.rule_id] = out.get(v.rule_id, 0) + 1
    return out


def report_json(active: List[Violation], suppressed: List[Violation],
                ctx: LintContext, rules: List[Rule],
                stream: TextIO) -> None:
    payload = {
        "ok": not active,
        "violations": [v.as_json() for v in active],
        "suppressed": [v.as_json() for v in suppressed],
        "counts": _counts(active),
        "suppressed_counts": _counts(suppressed),
        "files_scanned": len(ctx.files),
        "rules": {r.RULE_ID: r.SUMMARY for r in rules},
    }
    json.dump(payload, stream, indent=1, sort_keys=True)
    stream.write("\n")
