"""apexlint framework: rule registry, lint context, suppressions.

A *rule* is a class with a ``RULE_ID``/``SUMMARY`` and a ``check(ctx)``
generator yielding :class:`Violation`. Rules register themselves with the
:func:`register` decorator; :func:`run_lint` runs every (selected) rule
over a :class:`LintContext` and applies the suppression policy:

- ``# apexlint: disable=APX001 -- <justification>`` on a violation's line
  (or on the line directly above, for lines with no room) suppresses that
  rule at that site. The justification text after ``--`` is **mandatory**:
  a disable comment without one is itself a violation (APX000), so the
  repo can never accumulate silent opt-outs.
- Suppressed violations are counted and carried in the JSON report —
  a suppression is a visible, audited decision, not a deletion.

The context parses each file once (AST + source lines cached) so five
rules over ~200 files stay fast enough for tier-1.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Tuple, Type

# repo root = parent of tools/ (this file lives at tools/apexlint/core.py)
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

SUPPRESS_RE = re.compile(
    r"#\s*apexlint:\s*disable=(?P<rules>[A-Z0-9,\s]+?)"
    r"(?:\s*--\s*(?P<why>.*\S))?\s*$")


@dataclasses.dataclass
class Violation:
    """One finding: rule, location, message. ``suppressed``/``why`` are
    filled in by the framework when a justified disable comment matches."""

    rule_id: str
    path: str                 # repo-relative
    line: int
    message: str
    suppressed: bool = False
    justification: Optional[str] = None

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule_id} {self.message}"

    def as_json(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "rule": self.rule_id, "path": self.path, "line": self.line,
            "message": self.message,
        }
        if self.suppressed:
            out["suppressed"] = True
            out["justification"] = self.justification
        return out


@dataclasses.dataclass
class _Suppression:
    line: int                     # the line the comment sits on
    rules: Tuple[str, ...]
    justification: Optional[str]  # None → unjustified (an APX000 violation)
    used: bool = False


class SourceFile:
    """One parsed file: source, lines, AST (None when unparseable),
    suppression comments."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.path = relpath
        with open(abspath, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.parse_error: Optional[str] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(self.source)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"line {e.lineno}: {e.msg}"
        self.suppressions: List[_Suppression] = []
        if "apexlint" in self.source:
            # real COMMENT tokens only — a disable spelled inside a
            # docstring (this framework documents its own syntax...) is
            # prose, not a suppression
            try:
                tokens = list(tokenize.generate_tokens(
                    io.StringIO(self.source).readline))
            except (tokenize.TokenError, SyntaxError, IndentationError):
                tokens = []
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = SUPPRESS_RE.search(tok.string)
                if m:
                    rules = tuple(r.strip()
                                  for r in m.group("rules").split(",")
                                  if r.strip())
                    self.suppressions.append(
                        _Suppression(tok.start[0], rules, m.group("why")))

    def segment(self, node: ast.AST) -> str:
        """Source text of a node (used by rules for marker-comment
        evidence, e.g. ``# caller holds self._lock``)."""
        end = getattr(node, "end_lineno", node.lineno)
        return "\n".join(self.lines[node.lineno - 1:end])


class LintContext:
    """The scanned file set. ``files`` preserves a stable sorted order so
    reports are deterministic."""

    def __init__(self, root: str, paths: Optional[Iterable[str]] = None):
        self.root = os.path.abspath(root)
        self.files: List[SourceFile] = []
        self._by_path: Dict[str, SourceFile] = {}
        for p in self._collect(paths):
            rel = os.path.relpath(p, self.root)
            if rel.startswith(".."):
                # a file outside --root has no repo-relative identity, so
                # every path-scoped rule would silently skip it and the
                # run would read "clean" while checking nothing
                raise OSError(
                    f"{p} is outside the lint root {self.root} — pass "
                    f"--root, or lint from the repo that owns the file")
            sf = SourceFile(p, rel)
            self.files.append(sf)
            self._by_path[rel] = sf

    def _collect(self, paths: Optional[Iterable[str]]) -> List[str]:
        if paths is None:
            paths = [os.path.join(self.root, "apex_tpu"),
                     os.path.join(self.root, "tools")]
        out: List[str] = []
        for p in paths:
            p = p if os.path.isabs(p) else os.path.join(self.root, p)
            if os.path.isfile(p):
                out.append(p)
                continue
            if not os.path.isdir(p):
                # a typo'd CI path must be a loud usage error, not a
                # silent 0-files-scanned "clean" pass
                raise OSError(f"no such file or directory: {p}")
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        return sorted(set(out))

    def file(self, relpath: str) -> Optional[SourceFile]:
        return self._by_path.get(relpath)

    def iter_files(self, *, under: Optional[str] = None
                   ) -> Iterator[SourceFile]:
        """Files whose repo-relative path starts with ``under`` (a
        directory prefix like ``apex_tpu``); all files when None."""
        for sf in self.files:
            if under is None or sf.path == under or \
                    sf.path.startswith(under.rstrip(os.sep) + os.sep):
                yield sf


class Rule:
    """Base class. Subclasses set ``RULE_ID`` (``APXnnn``) and ``SUMMARY``
    and implement ``check(ctx)`` yielding :class:`Violation`."""

    RULE_ID = "APX000"
    SUMMARY = ""

    def check(self, ctx: LintContext) -> Iterator[Violation]:
        raise NotImplementedError

    def violation(self, sf: SourceFile, line: int, message: str) -> Violation:
        return Violation(self.RULE_ID, sf.path, line, message)


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator: add a rule to the registry (keyed by RULE_ID)."""
    if cls.RULE_ID in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.RULE_ID}")
    _REGISTRY[cls.RULE_ID] = cls
    return cls


def get_rules(only: Optional[Iterable[str]] = None) -> List[Rule]:
    """Instantiate registered rules (all, or the ``only`` subset).
    Importing ``tools.apexlint.rules`` populates the registry."""
    from . import rules  # noqa: F401  (side effect: rule registration)

    ids = sorted(_REGISTRY)
    if only is not None:
        only = list(only)
        unknown = sorted(set(only) - set(ids))
        if unknown:
            raise KeyError(f"unknown rule id(s): {', '.join(unknown)}; "
                           f"known: {', '.join(ids)}")
        ids = [i for i in ids if i in only]
    return [_REGISTRY[i]() for i in ids]


def _apply_suppressions(ctx: LintContext, violations: List[Violation],
                        run_rules: Iterable[str]) -> List[Violation]:
    """Mark violations covered by a justified disable on the same line or
    the line directly above; emit APX000 for unjustified disables and for
    suppressions that no longer suppress anything."""
    run_rules = set(run_rules)
    for v in violations:
        sf = ctx.file(v.path)
        if sf is None:
            continue
        for sup in sf.suppressions:
            if sup.line not in (v.line, v.line - 1):
                continue
            if v.rule_id not in sup.rules or v.rule_id == "APX000":
                continue
            sup.used = True
            if sup.justification:
                v.suppressed = True
                v.justification = sup.justification
            # an unjustified disable does NOT suppress — the violation
            # stands, and APX000 below flags the comment itself
    extra: List[Violation] = []
    for sf in ctx.files:
        for sup in sf.suppressions:
            if not sup.justification:
                extra.append(Violation(
                    "APX000", sf.path, sup.line,
                    f"suppression of {','.join(sup.rules)} without a "
                    f"justification (write `# apexlint: "
                    f"disable={','.join(sup.rules)} -- <why>`)"))
            elif not sup.used and set(sup.rules) <= run_rules:
                # a stale opt-out hides nothing but reads as if it did —
                # the audited-decision policy cuts both ways. Only when
                # every referenced rule actually ran: a --rules subset
                # cannot judge a foreign suppression unused.
                extra.append(Violation(
                    "APX000", sf.path, sup.line,
                    f"unused suppression of {','.join(sup.rules)} — no "
                    f"matching violation on this line; delete the stale "
                    f"comment"))
    return violations + extra


def run_lint(root: str = REPO_ROOT,
             paths: Optional[Iterable[str]] = None,
             only: Optional[Iterable[str]] = None
             ) -> Tuple[List[Violation], List[Violation], LintContext]:
    """Run (selected) rules over ``paths``; returns ``(active,
    suppressed, ctx)`` with active sorted by (path, line, rule)."""
    ctx = LintContext(root, paths)
    rules = get_rules(only)
    found: List[Violation] = []
    for rule in rules:
        found.extend(rule.check(ctx))
    found = _apply_suppressions(ctx, found,
                                [r.RULE_ID for r in rules])
    for sf in ctx.files:
        if sf.parse_error is not None:
            found.append(Violation("APX000", sf.path, 0,
                                   f"unparseable: {sf.parse_error}"))
    found.sort(key=lambda v: (v.path, v.line, v.rule_id))
    active = [v for v in found if not v.suppressed]
    suppressed = [v for v in found if v.suppressed]
    return active, suppressed, ctx
