"""apexlint — AST-based invariant linter for the apex_tpu repo.

The codebase rests on invariants that used to be enforced only by
convention or one-off checks: jitted code must stay host-effect-free,
cross-thread state must be mutated behind its lock, event names must be
registered in the goodput schema, durable artifacts must commit
atomically, and duration math must use a monotonic clock. This package
makes each of them a mechanical check:

========  ==================================================================
APX001    trace purity — no host effects reachable from traced code
          (``jax.jit`` / ``shard_map`` / ``lax.scan`` / ``pallas_call``)
APX002    lock discipline — attributes mutated under ``self._lock`` may not
          be read-modify-written outside it
APX003    event schema — every literal ``publish_event`` /
          ``structured_warning`` name must be registered in
          ``apex_tpu.monitor.goodput``
APX004    durability — durable artifacts commit via ``.tmp`` +
          ``os.replace`` (the former ``tools/check_durability.py``)
APX005    clock hygiene — no ``time.time()`` deltas in duration math, no
          ungated ``print`` outside CLI / logging modules
APX000    suppression discipline — every ``# apexlint: disable=`` comment
          must carry a justification (always on; cannot be suppressed)
========  ==================================================================

Run ``apex-tpu-lint`` (or ``python -m tools.apexlint``) from the repo
root; see docs/static-analysis.md for the rule catalog, the suppression
policy, and how to add a rule.
"""

from .core import (  # noqa: F401
    LintContext,
    Rule,
    Violation,
    get_rules,
    register,
    run_lint,
)

__all__ = ["LintContext", "Rule", "Violation", "get_rules", "register",
           "run_lint"]
