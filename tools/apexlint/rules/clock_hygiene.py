"""APX005 — clock hygiene: monotonic deltas, no ungated prints.

Two checks over ``apex_tpu/``:

**time.time() deltas.** ``time.time()`` is wall clock — NTP steps it
backwards and forwards — so subtracting two reads is not a duration.
Every duration/tracing measurement must use ``time.monotonic()`` or
``time.perf_counter()``. The rule flags any subtraction whose operands
involve a ``time.time()`` call directly or a name/attribute that is
assigned ``time.time()`` anywhere in the same file. Bare ``time.time()``
reads that never enter arithmetic (wall-clock provenance stamps like a
checkpoint's ``created`` field) are fine — that is exactly what wall
clock is for.

**ungated print.** PR 4 established that console output in library code
is rank-0-gated (``utils.logging.is_rank_zero``) so an N-host run prints
one banner, not N interleaved ones. The rule flags ``print`` calls in
``apex_tpu/`` unless (a) the module is a CLI entry point (``*/cli.py``,
``bench_cli.py`` — a CLI's stdout IS its interface and CLIs are
single-process), (b) the module is ``utils/logging.py`` (the funnel
every gated print is supposed to go through), or (c) the enclosing
function shows rank-0 gating (``is_rank_zero`` in its source). A
deliberate every-rank print (the watchdog's stack dump) carries a
justified ``# apexlint: disable=APX005`` instead.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, Set

from ..core import LintContext, Rule, SourceFile, Violation, register

# modules whose stdout/stderr output is their interface (exact basenames
# — a suffix match would silently exempt any future `*cli.py` module)
PRINT_OK_FILES = frozenset({"cli.py", "bench_cli.py", "lint_cli.py"})
PRINT_OK_PATHS = (os.path.join("utils", "logging.py"),)
GATE_EVIDENCE = "is_rank_zero"


def _is_time_time(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "time"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time")


def _target_keys(node: ast.AST) -> Set[str]:
    """Stable keys for assignment targets we track: bare names and
    ``self.x`` / ``obj.x`` attributes (keyed by their dotted tail)."""
    keys: Set[str] = set()
    if isinstance(node, ast.Name):
        keys.add(node.id)
    elif isinstance(node, ast.Attribute):
        keys.add(node.attr)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            keys |= _target_keys(elt)
    return keys


class _FileScan(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        # names/attrs assigned time.time() anywhere in the file — the
        # "stored wall-clock read" half of a delta
        self.wall_names: Set[str] = set()
        self.subs: list = []      # (lineno, node) Sub BinOps
        self.prints: list = []    # (lineno, enclosing function node|None)
        self._func_stack: list = []

    def visit_FunctionDef(self, node):
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        if _is_time_time(node.value):
            for t in node.targets:
                self.wall_names |= _target_keys(t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        # `self._t0: float = time.time()` stores wall clock all the same
        if node.value is not None and _is_time_time(node.value):
            self.wall_names |= _target_keys(node.target)
        self.generic_visit(node)

    def visit_BinOp(self, node):
        if isinstance(node.op, ast.Sub):
            self.subs.append(node)
        self.generic_visit(node)

    def visit_Call(self, node):
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            self.prints.append(
                (node.lineno,
                 self._func_stack[-1] if self._func_stack else None))
        self.generic_visit(node)


def _sub_involves_wall_clock(node: ast.BinOp, wall_names: Set[str]) -> bool:
    for side in (node.left, node.right):
        for sub in ast.walk(side):
            if _is_time_time(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in wall_names:
                return True
            if isinstance(sub, ast.Attribute) and sub.attr in wall_names:
                return True
    return False


@register
class ClockHygieneRule(Rule):
    RULE_ID = "APX005"
    SUMMARY = ("durations use monotonic clocks (no time.time() deltas); "
               "no ungated print in library code")

    SCOPE = "apex_tpu"

    def check(self, ctx: LintContext) -> Iterator[Violation]:
        for sf in ctx.iter_files(under=self.SCOPE):
            if sf.tree is None:
                continue
            scan = _FileScan(sf)
            scan.visit(sf.tree)
            for node in scan.subs:
                if _sub_involves_wall_clock(node, scan.wall_names):
                    yield self.violation(
                        sf, node.lineno,
                        "duration computed from time.time() — wall clock "
                        "steps under NTP; use time.monotonic() or "
                        "time.perf_counter() for deltas")
            if os.path.basename(sf.path) in PRINT_OK_FILES or \
                    any(sf.path.endswith(p) for p in PRINT_OK_PATHS):
                continue
            for lineno, fn in scan.prints:
                seg = sf.segment(fn) if fn is not None else sf.source
                if GATE_EVIDENCE in seg:
                    continue
                yield self.violation(
                    sf, lineno,
                    "ungated print in library code — gate on "
                    "utils.logging.is_rank_zero(), publish a bus event, "
                    "or route through utils.logging")
