"""APX004 — durable artifacts commit atomically (.tmp + os.replace).

The framework port of ``tools/check_durability.py`` (which remains as a
thin CLI shim over this rule). A checkpoint or flight-recorder dump
written with a bare ``open(path, "w")`` / ``np.savez(path)`` can be torn
by a crash and then loaded (or choked on) at restore — the exact failure
class ``apex_tpu.resilience`` exists to close. The rule walks the
package AST for write calls in checkpoint-flavored code and fails unless
the enclosing function shows the atomic-commit discipline: stage to
``.tmp`` + publish with ``os.replace``, route through the
``Filesystem.write_bytes`` seam, or write only to an in-memory buffer.

Scope (kept deliberately narrow to stay false-positive-free):

- files whose path contains ``checkpoint``,
- the flight recorder (``monitor/flight``) — its crash-time postmortem
  dump is exactly the artifact a torn write would make worthless,
- functions whose name contains save/checkpoint/ckpt/manifest/dump
  anywhere in ``apex_tpu/``.

Sharded-checkpoint modules (``resilience/distributed``) get two stricter
rules on top — the two-phase commit's whole crash-safety argument rests
on them: EVERY write (the seam included) must visibly stage into
``.tmp``, and the publish must go through ``os.replace``
(``os.rename``/``shutil.move`` are flagged as non-atomic).
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Tuple

from ..core import LintContext, Rule, Violation, register

CKPT_NAME_HINTS = ("save", "checkpoint", "ckpt", "manifest", "dump")
WRITE_MODES = ("w", "wb", "w+", "wb+", "x", "xb")
# evidence of the atomic-commit discipline inside a function's source
SAFE_MARKERS = (".tmp", "os.replace")
# writes through these are safe by construction (in-memory, or the fs seam)
SAFE_CALL_HINTS = ("BytesIO", "write_bytes", "StringIO")
ALLOWED_FUNCS = {"write_bytes"}  # the seam's own implementation

# sharded-checkpoint modules: the stricter ruleset applies
SHARDED_PATH_HINTS = (os.path.join("resilience", "distributed"),)
# flight-recorder module: every on-disk dump is a durable artifact
FLIGHT_PATH_HINTS = (os.path.join("monitor", "flight"),)
# evidence a sharded write targets the .tmp staging dir
STAGING_MARKERS = (".tmp", "_TMP_SUFFIX")
# non-atomic publish calls: (module attr, call name)
RENAME_CALLS = {("os", "rename"), ("shutil", "move")}


def _is_write_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in ("save", "savez",
                                                   "savez_compressed"):
        root = f.value
        if isinstance(root, ast.Name) and root.id in ("np", "numpy"):
            return True
    if isinstance(f, ast.Name) and f.id == "open":
        mode = None
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        return isinstance(mode, str) and mode in WRITE_MODES
    return False


def _is_seam_write(node: ast.Call) -> bool:
    """A write through the Filesystem seam (``*.write_bytes(...)``) — safe
    in ordinary checkpoint code, but in sharded modules it must still
    target ``.tmp`` staging."""
    return isinstance(node.func, ast.Attribute) and \
        node.func.attr == "write_bytes"


def _is_rename_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and (f.value.id, f.attr) in RENAME_CALLS)


def _path_arg_staged(node: ast.Call) -> bool:
    """True when the write's path argument visibly derives from a staging
    variable (``tmp``/``staging``) — e.g. ``os.path.join(tmp, name)`` —
    the strongest static evidence the bytes land inside the staging dir."""
    if not node.args:
        return False
    for sub in ast.walk(node.args[0]):
        if isinstance(sub, ast.Name) and (
                "tmp" in sub.id.lower() or "staging" in sub.id.lower()):
            return True
    return False


def _writes_to_path(node: ast.Call) -> bool:
    """Distinguish a filesystem write from a serialize-into-buffer: np.save
    into an ``io.BytesIO`` (a bare buffer Name) is in-memory; a string
    constant, f-string, concatenation, ``os.path.join(...)`` or a
    path-flavored variable name is a real destination."""
    if isinstance(node.func, ast.Name):  # open(...) — arg IS the path
        return True
    if not node.args:
        return False
    arg = node.args[0]
    if isinstance(arg, (ast.Constant, ast.JoinedStr, ast.BinOp, ast.Call)):
        return True
    if isinstance(arg, ast.Name):
        return any(h in arg.id.lower()
                   for h in ("path", "file", "dir", "dst", "target"))
    return True  # attribute/subscript etc: assume a path, stay strict


def check_source(path: str, src: str) -> List[Tuple[int, str]]:
    """Durability findings for one file's source: ``[(line, message)]``.
    Shared by the rule below and the ``check_durability.py`` shim."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [(e.lineno or 0, f"unparseable: {e.msg}")]
    norm = os.path.normpath(path).lower()
    ckpt_file = "checkpoint" in os.path.basename(path).lower()
    sharded_file = any(h in norm for h in SHARDED_PATH_HINTS)
    flight_file = any(h in norm for h in FLIGHT_PATH_HINTS)
    lines = src.splitlines()
    violations: List[Tuple[int, str]] = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: List[ast.AST] = []

        def visit_FunctionDef(self, node):
            self.stack.append(node)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            fn = self.stack[-1] if self.stack else None
            name = fn.name if fn is not None else "<module>"
            seg = ("\n".join(lines[fn.lineno - 1:fn.end_lineno])
                   if fn is not None else src)
            if _is_write_call(node):
                in_scope = ckpt_file or sharded_file or flight_file or any(
                    h in name.lower() for h in CKPT_NAME_HINTS)
                if in_scope and name not in ALLOWED_FUNCS:
                    safe = (all(m in seg for m in SAFE_MARKERS)
                            or any(h in seg for h in SAFE_CALL_HINTS))
                    if not safe:
                        violations.append((
                            node.lineno,
                            f"{name}: non-atomic write on a durable-"
                            f"artifact path (want .tmp + os.replace, or "
                            f"the Filesystem.write_bytes seam)"))
            if sharded_file and (_is_seam_write(node) or (
                    _is_write_call(node) and _writes_to_path(node))):
                # sharded rule 1: every write — seam included — must show
                # the .tmp staging discipline: either its path argument
                # derives from the staging variable, or the enclosing
                # function carries the staging markers
                if not _path_arg_staged(node) and \
                        not any(m in seg for m in STAGING_MARKERS):
                    violations.append((
                        node.lineno,
                        f"{name}: sharded-checkpoint write outside .tmp "
                        f"staging (every byte must stage under "
                        f"<step>.tmp until the rank-0 replace)"))
            if (sharded_file or ckpt_file) and _is_rename_call(node):
                # sharded rule 2: the publish is ONE os.replace — rename/
                # move have non-atomic or copy semantics across filesystems
                violations.append((
                    node.lineno,
                    f"{name}: checkpoint publish must use os.replace "
                    f"(os.rename/shutil.move are not the atomic commit)"))
            self.generic_visit(node)

    V().visit(tree)
    return violations


@register
class DurabilityRule(Rule):
    RULE_ID = "APX004"
    SUMMARY = ("durable artifacts (checkpoints, flight dumps) commit via "
               ".tmp staging + one os.replace")

    SCOPE = "apex_tpu"

    def check(self, ctx: LintContext) -> Iterator[Violation]:
        for sf in ctx.iter_files(under=self.SCOPE):
            for lineno, msg in check_source(sf.path, sf.source):
                yield self.violation(sf, lineno, msg)
