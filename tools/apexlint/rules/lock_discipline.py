"""APX002 — lock discipline: guarded state is not RMW'd lock-free.

The scheduler/bus/watchdog/flight-recorder state is mutated from several
threads (heartbeat threads, bus subscribers, a deployment calling
``ServeScheduler.abort`` mid-run) behind ad-hoc locks, and the PR-6
``ChromeTraceWriter`` framing race was caught only in review. This rule
makes the discipline mechanical:

For every class (or module) that owns locks — attributes/globals
assigned ``threading.Lock()`` / ``threading.RLock()`` — the rule
collects the names (attributes/globals) **ever mutated inside a** ``with
self._lock:`` **block**, remembering *which* lock. Those are the
*guarded* names: somebody decided they need a lock, so every
read-modify-write must hold **that** lock. Flagged:

- a RMW of a guarded name with **no** lock held:
  ``self.x += 1`` / ``x += 1`` (augmented assignment),
  ``self.x[k] = v`` / ``del self.x[k]`` (container element writes),
  ``self.x.append(...)`` and the other mutating container methods,
  ``self.x = f(self.x)`` (an assignment whose RHS reads the same name);
- a RMW of a guarded name under a **different** lock than the one(s)
  guarding it elsewhere (two locks "protecting" the same name protect
  nothing).

Plain rebinding (``self.x = fresh_value``) stays legal outside the lock
— it is atomic under the GIL and the idiom for publishing a new
snapshot. ``__init__`` is exempt (the object is not shared yet). Helper
methods entered with the lock already held declare it with a marker
comment in their body — ``# caller holds self._lock`` — which the rule
treats as holding that lock (the existing ``ChromeTraceWriter._emit``
idiom; a marker naming no known lock counts as holding all of them).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import FrozenSet, Iterator, List, Optional, Set, Tuple

from ..core import LintContext, Rule, SourceFile, Violation, register

MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popitem", "popleft", "remove", "discard",
    "clear",
})
HOLDS_MARKER_RE = re.compile(r"caller holds\s+(?:self\.)?(\w+)")
HOLDS_MARKER = "caller holds"
LOCK_CTORS = ("Lock", "RLock")
EXEMPT_METHODS = ("__init__",)


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in LOCK_CTORS
    return isinstance(f, ast.Attribute) and f.attr in LOCK_CTORS and \
        isinstance(f.value, ast.Name) and f.value.id == "threading"


def _lock_assign_targets(stmt: ast.AST) -> List[ast.AST]:
    """Assignment targets when ``stmt`` binds a Lock()/RLock() — covers
    plain AND annotated assignment (``self._lock: Lock = Lock()``), so a
    type annotation cannot silently blind the rule."""
    if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
        return list(stmt.targets)
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None and \
            _is_lock_ctor(stmt.value):
        return [stmt.target]
    return []


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@dataclasses.dataclass
class _Mutation:
    name: str          # attribute (class mode) or global (module mode)
    lineno: int
    rmw: bool          # read-modify-write (vs. plain rebinding)
    held: FrozenSet[str]   # lock names held at the mutation site
    func: str          # enclosing method/function name
    desc: str

    @property
    def locked(self) -> bool:
        return bool(self.held)


class _ScopeWalker(ast.NodeVisitor):
    """Walk one function body tracking which locks are held and
    collecting mutations of self-attrs (class mode) or known globals
    (module mode)."""

    def __init__(self, sf: SourceFile, locks: Set[str], func: ast.AST,
                 func_name: str, globals_: Optional[Set[str]] = None):
        self.sf = sf
        self.locks = locks
        self.func_name = func_name
        self.globals = globals_     # None → class mode (track self.attr)
        self.held: List[str] = []
        # a "caller holds <lock>" marker makes the whole body hold that
        # lock (an unrecognized lock name degrades to holding all — the
        # marker is evidence of intent, not grounds for a false positive)
        seg = sf.segment(func)
        if HOLDS_MARKER in seg:
            named = [m for m in HOLDS_MARKER_RE.findall(seg)
                     if m in locks]
            self.held.extend(named if named else sorted(locks))
        self.mutations: List[_Mutation] = []

    # ---- lock tracking --------------------------------------------------
    def _lock_name(self, node: ast.AST) -> Optional[str]:
        if self.globals is None:
            attr = _self_attr(node)
            return attr if attr is not None and attr in self.locks \
                else None
        if isinstance(node, ast.Name) and node.id in self.locks:
            return node.id
        return None

    def visit_With(self, node: ast.With):
        entered = [n for n in (self._lock_name(item.context_expr)
                               for item in node.items) if n is not None]
        self.held.extend(entered)
        for stmt in node.body:
            self.visit(stmt)
        if entered:
            del self.held[-len(entered):]

    def visit_FunctionDef(self, node):
        # nested defs inherit the lexical locked state at their definition
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    # ---- mutation collection -------------------------------------------
    def _target_name(self, node: ast.AST) -> Optional[str]:
        """The tracked name a store/mutation targets, or None."""
        if self.globals is None:
            return _self_attr(node)
        if isinstance(node, ast.Name) and node.id in self.globals:
            return node.id
        return None

    def _reads(self, expr: ast.AST, name: str) -> bool:
        for sub in ast.walk(expr):
            if self._target_name(sub) == name:
                return True
        return False

    def _record(self, name: str, lineno: int, rmw: bool, desc: str) -> None:
        self.mutations.append(_Mutation(
            name, lineno, rmw, frozenset(self.held), self.func_name, desc))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for tgt in targets:
                name = self._target_name(tgt)
                if name is not None:
                    rmw = self._reads(node.value, name)
                    self._record(name, node.lineno, rmw,
                                 "assignment reading the same attribute"
                                 if rmw else "rebinding")
                elif isinstance(tgt, ast.Subscript):
                    name = self._target_name(tgt.value)
                    if name is not None:
                        self._record(name, node.lineno, True,
                                     "container element write")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        name = self._target_name(node.target)
        if name is not None:
            self._record(name, node.lineno, True, "augmented assignment")
        elif isinstance(node.target, ast.Subscript):
            name = self._target_name(node.target.value)
            if name is not None:
                self._record(name, node.lineno, True,
                             "container element write")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                name = self._target_name(t.value)
                if name is not None:
                    self._record(name, node.lineno, True,
                                 "container element delete")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
            name = self._target_name(f.value)
            if name is not None:
                self._record(name, node.lineno, True, f".{f.attr}()")
        self.generic_visit(node)


def _analyze(sf: SourceFile, scope_desc: str, locks: Set[str],
             funcs: List[Tuple[str, ast.AST]],
             globals_: Optional[Set[str]]) -> Iterator[Tuple[int, str]]:
    """Shared class/module analysis: collect mutations per function, form
    the per-lock guarded sets, flag lock-free or wrong-lock RMW."""
    all_mut: List[_Mutation] = []
    for fname, fnode in funcs:
        w = _ScopeWalker(sf, locks, fnode, fname, globals_)
        for stmt in fnode.body:
            w.visit(stmt)
        all_mut.extend(w.mutations)
    considered = [m for m in all_mut
                  if m.name not in locks and m.func not in EXEMPT_METHODS]
    # name → every lock set it was mutated under (the guard evidence)
    guard_sets = {}
    for m in considered:
        if m.locked:
            guard_sets.setdefault(m.name, []).append(m.held)
    lock_ref = ("" if globals_ is not None else "self.") + sorted(locks)[0]
    for m in considered:
        if not m.rmw or m.name not in guard_sets:
            continue
        if not m.locked:
            guards = sorted(set().union(*guard_sets[m.name]))
            yield (m.lineno,
                   f"{scope_desc}.{m.func}: lock-free {m.desc} of "
                   f"{m.name!r}, which is elsewhere mutated under "
                   f"{', '.join(guards)} — take the lock (or mark the "
                   f"helper `# {HOLDS_MARKER} {lock_ref}`)")
        elif any(not (m.held & other) for other in guard_sets[m.name]):
            # held a lock — but a DIFFERENT one than another mutation of
            # the same name holds: the two sites do not exclude each other
            others = sorted(set().union(
                *(o for o in guard_sets[m.name] if not (m.held & o))))
            yield (m.lineno,
                   f"{scope_desc}.{m.func}: {m.desc} of {m.name!r} under "
                   f"{', '.join(sorted(m.held))}, but it is elsewhere "
                   f"mutated under {', '.join(others)} — two locks "
                   f"guarding one name exclude nothing; pick one")


@register
class LockDisciplineRule(Rule):
    RULE_ID = "APX002"
    SUMMARY = ("state mutated under a lock may not be read-modify-"
               "written outside it (or under a different lock)")

    def check(self, ctx: LintContext) -> Iterator[Violation]:
        for sf in ctx.files:
            if sf.tree is None:
                continue
            # ---- class scopes ----------------------------------------
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                methods = [(n.name, n) for n in node.body
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
                locks: Set[str] = set()
                for stmt in node.body:  # class-attr locks: _lock = Lock()
                    for t in _lock_assign_targets(stmt):
                        if isinstance(t, ast.Name):
                            locks.add(t.id)
                for _, meth in methods:
                    for sub in ast.walk(meth):
                        for t in _lock_assign_targets(sub):
                            attr = _self_attr(t)
                            if attr is not None:
                                locks.add(attr)
                if not locks:
                    continue
                for lineno, msg in _analyze(sf, node.name, locks,
                                            methods, None):
                    yield self.violation(sf, lineno, msg)
            # ---- module scope ----------------------------------------
            assert isinstance(sf.tree, ast.Module)
            mod_locks: Set[str] = set()
            mod_globals: Set[str] = set()
            for stmt in sf.tree.body:
                lock_targets = _lock_assign_targets(stmt)
                if lock_targets:
                    mod_locks |= {t.id for t in lock_targets
                                  if isinstance(t, ast.Name)}
                elif isinstance(stmt, ast.Assign):
                    mod_globals |= {t.id for t in stmt.targets
                                    if isinstance(t, ast.Name)}
                elif isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    mod_globals.add(stmt.target.id)
            if not mod_locks:
                continue
            funcs = [(n.name, n) for n in sf.tree.body
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            for lineno, msg in _analyze(sf, sf.path, mod_locks, funcs,
                                        mod_globals):
                yield self.violation(sf, lineno, msg)
