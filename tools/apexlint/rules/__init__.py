"""Rule modules — importing this package registers every rule."""

from . import clock_hygiene  # noqa: F401
from . import durability  # noqa: F401
from . import event_schema  # noqa: F401
from . import lock_discipline  # noqa: F401
from . import trace_purity  # noqa: F401
