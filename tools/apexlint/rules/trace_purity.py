"""APX001 — trace purity: no host effects reachable from traced code.

PR 2's "no-callback jaxpr" asserts protect two jitted functions; this
rule protects all of them. It builds an intra-package call graph and
walks reachability from every **traced root**:

- functions decorated with ``jax.jit`` (bare, or via
  ``functools.partial(jax.jit, ...)``),
- callables passed to ``jax.jit(...)`` / ``shard_map(...)`` /
  ``jax.lax.scan(...)`` / ``pl.pallas_call(...)`` (by name, ``self.``
  method, lambda, or through ``functools.partial``).

Any function reachable from a root may not perform a **host effect**:

- clock reads (``time.*`` — a ``perf_counter()`` inside traced code is
  constant-folded at trace time and stamps every step with the same
  value),
- bus/log output (``publish_event``/``structured_warning``/
  ``one_time_warning``/``print`` — fires once per *trace*, not per step,
  which is exactly the misleading telemetry PR 2 banned),
- file I/O (``open``),
- host syncs (``.item()`` — the decidable spelling of the
  ``.item()``/``float()``-on-traced-value class; bare ``float(x)`` is
  statically indistinguishable from legal trace-time coercion of static
  config and is not flagged),
- callback escapes (``io_callback``/``pure_callback``/
  ``jax.debug.print``/``jax.debug.callback`` — the "no-callback jaxpr"
  invariant itself),
- live-metrics mutations (``.record()``/``.observe()``/``.inc()`` — a
  monitor.export registry sample taken inside traced code lands once per
  trace, not per step; record around the jitted call).

The traversal stops at *sanctioned trace-time boundaries* — functions
whose whole purpose is host-side static resolution during trace
(:data:`BOUNDARY_FUNCS`, e.g. the autotuner's ``tuned_params``: it reads
the tune cache and publishes provenance events once per trace by
design). Resolution is static and conservative: bare names lexically,
``self.m`` within the class, ``mod.f``/from-imports across apex_tpu
modules; calls through values it cannot resolve (flax ``.apply``,
callables passed as arguments) are not followed.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import LintContext, Rule, SourceFile, Violation, register

# sanctioned trace-time host work: static geometry/config resolution that
# must run during trace and is documented to do so. Crossing one of these
# names ends the traversal — their internals are host code by design.
BOUNDARY_FUNCS = frozenset({
    "tuned_params",     # tune.api: cache lookup + autotune provenance
})

EFFECT_NAME_CALLS = frozenset({
    "publish_event", "structured_warning", "one_time_warning",
    "deprecated_warning", "print", "open", "input",
    "io_callback", "pure_callback",
})
EFFECT_ATTR_CALLS = frozenset({"item", "io_callback", "pure_callback"})
# live-metrics mutation verbs (monitor.export registry: Counter.inc,
# Histogram.record/observe). Inside traced code these fire once per
# TRACE, not per step — the same silently-wrong-telemetry class as
# publish_event. ``.set`` is deliberately absent: ``x.at[i].set(v)`` is
# the jnp functional-update idiom all over legitimately traced code
# (its subscripted chain never resolves here, but the name must not
# invite the confusion either).
METRIC_ATTR_CALLS = frozenset({"record", "observe", "inc"})
TRACE_WRAPPERS = ("jit", "pallas_call", "shard_map")


def _attr_chain(node: ast.AST) -> List[str]:
    """``jax.lax.scan`` → ["jax", "lax", "scan"]; [] when not a plain
    dotted chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _unwrap_partial(node: ast.AST) -> ast.AST:
    """``functools.partial(f, ...)`` → ``f`` (recursively)."""
    while isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "partial" and node.args:
            node = node.args[0]
        else:
            break
    return node


def _is_trace_wrapper(func: ast.AST) -> Optional[str]:
    """'jit' / 'pallas_call' / 'shard_map' / 'scan' when ``func`` is a
    call target that traces its first callable argument."""
    chain = _attr_chain(func)
    if not chain:
        return None
    tail = chain[-1]
    if tail in TRACE_WRAPPERS:
        return tail
    if tail == "scan" and (len(chain) == 1 or chain[-2] == "lax"):
        return "scan"
    return None


class _FuncInfo:
    """One function/method/lambda node in the call graph."""

    def __init__(self, key: Tuple[str, ...], node: ast.AST, sf: SourceFile,
                 module: str, scope: Tuple[str, ...],
                 class_name: Optional[str]):
        self.key = key
        self.node = node
        self.sf = sf
        self.module = module
        self.scope = scope          # lexical scope path above this def
        self.class_name = class_name
        self.name = key[-1]
        self.is_root = False
        self.root_why = ""
        self.calls: List[Tuple] = []            # resolvable call refs
        self.effects: List[Tuple[int, str]] = []
        self.loads: Set[str] = set()            # bare names read in body

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 0)


class _ModuleIndex:
    def __init__(self, module: str):
        self.module = module
        # bare alias → ("module", dotted) | ("from", module, original)
        self.imports: Dict[str, Tuple] = {}


class _Indexer:
    """Pass 1 over one module: register every function node, record its
    calls/effects/loads, note imports and traced-root sites."""

    def __init__(self, rule: "TracePurityRule", sf: SourceFile,
                 module: str):
        self.rule = rule
        self.sf = sf
        self.module = module
        self.idx = _ModuleIndex(module)
        self.lambda_count = 0

    # ---- top-level drive ------------------------------------------------
    def index(self, tree: ast.Module) -> None:
        # module-level statements form a synthetic scope: they can carry
        # roots (`step = jax.jit(fn)` at import time) but are not
        # themselves traced
        mod_info = _FuncInfo((self.module, "<module>"), tree, self.sf,
                             self.module, (), None)
        for stmt in tree.body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self._record_import(stmt)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_func(stmt, (), None)
            elif isinstance(stmt, ast.ClassDef):
                self._index_class(stmt, ())
            else:
                self._scan_stmt(mod_info, stmt, set(), (), None,
                                effects=False)

    def _record_import(self, node) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    self.idx.imports[alias.asname] = ("module", alias.name)
                else:
                    root = alias.name.split(".")[0]
                    self.idx.imports[root] = ("module", root)
            return
        if node.level:  # relative: resolve against this module's package
            parts = self.module.split(".")
            base = ".".join(parts[:len(parts) - node.level])
            mod = f"{base}.{node.module}" if node.module else base
        else:
            mod = node.module or ""
        for alias in node.names:
            name = alias.asname or alias.name
            # alias may be a function in `mod` or the submodule
            # `mod.name`; resolution tries both at lookup time
            self.idx.imports[name] = ("from", mod, alias.name)

    # ---- registration ---------------------------------------------------
    def _register(self, name: str, node: ast.AST, scope: Tuple[str, ...],
                  cls: Optional[str], parent_is_class: bool) -> _FuncInfo:
        key = (self.module,) + scope + (name,)
        info = _FuncInfo(key, node, self.sf, self.module, scope, cls)
        self.rule.funcs[key] = info
        self.rule.by_module_scope.setdefault(
            (self.module, scope), {})[name] = info
        if parent_is_class and cls is not None:
            self.rule.methods.setdefault(
                (self.module, cls), {})[name] = info
        return info

    def _index_class(self, node: ast.ClassDef,
                     scope: Tuple[str, ...]) -> None:
        inner = scope + (node.name,)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_func(stmt, inner, node.name,
                                 parent_is_class=True)
            elif isinstance(stmt, ast.ClassDef):
                self._index_class(stmt, inner)

    def _index_func(self, node, scope: Tuple[str, ...],
                    cls: Optional[str],
                    parent_is_class: bool = False) -> None:
        info = self._register(node.name, node, scope, cls, parent_is_class)
        for dec in node.decorator_list:
            chain = _attr_chain(_unwrap_partial(dec))
            if chain and chain[-1] == "jit":
                info.is_root = True
                info.root_why = "@jit"
        params = self._params(node)
        inner = scope + (node.name,)
        for stmt in node.body:
            self._index_nested_or_scan(info, stmt, params, inner, cls)

    def _index_lambda(self, node: ast.Lambda, scope: Tuple[str, ...],
                      cls: Optional[str]) -> _FuncInfo:
        self.lambda_count += 1
        name = f"<lambda:{node.lineno}:{self.lambda_count}>"
        info = self._register(name, node, scope, cls, False)
        self._scan_expr_tree(info, node.body, self._params(node),
                             scope + (name,), cls)
        return info

    def _index_nested_or_scan(self, info: _FuncInfo, stmt: ast.AST,
                              params: Set[str], scope: Tuple[str, ...],
                              cls: Optional[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._index_func(stmt, scope, cls)
            return
        if isinstance(stmt, ast.ClassDef):
            self._index_class(stmt, scope)
            return
        self._scan_stmt(info, stmt, params, scope, cls, effects=True)

    @staticmethod
    def _params(node) -> Set[str]:
        a = node.args
        out = {arg.arg for arg in a.posonlyargs + a.args + a.kwonlyargs}
        if a.vararg:
            out.add(a.vararg.arg)
        if a.kwarg:
            out.add(a.kwarg.arg)
        out.discard("self")
        return out

    # ---- body scan ------------------------------------------------------
    def _scan_stmt(self, info: _FuncInfo, stmt: ast.AST, params: Set[str],
                   scope: Tuple[str, ...], cls: Optional[str],
                   effects: bool) -> None:
        """Scan one statement, descending into control flow but treating
        nested defs/lambdas as separate graph nodes."""
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            # function-local imports (the repo's cycle-avoidance idiom)
            # merge into the module's table — resolution is name-based
            self._record_import(stmt)
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_func(node, scope, cls)
                continue
            if isinstance(node, ast.ClassDef):
                self._index_class(node, scope)
                continue
            if isinstance(node, ast.Lambda):
                self._index_lambda(node, scope, cls)
                continue
            self._scan_stmt(info, node, params, scope, cls, effects)
        if isinstance(stmt, ast.Name) and isinstance(stmt.ctx, ast.Load):
            info.loads.add(stmt.id)
        if isinstance(stmt, ast.Call):
            self._scan_call(info, stmt, params, scope, cls,
                            effects=effects)

    def _scan_expr_tree(self, info: _FuncInfo, expr: ast.AST,
                        params: Set[str], scope: Tuple[str, ...],
                        cls: Optional[str]) -> None:
        """Lambda bodies: scan the expression tree itself."""
        self._scan_stmt(info, expr, params, scope, cls, effects=True)
        if isinstance(expr, ast.Call):
            self._scan_call(info, expr, params, scope, cls, effects=True)
        if isinstance(expr, ast.Name) and isinstance(expr.ctx, ast.Load):
            info.loads.add(expr.id)

    def _scan_call(self, info: _FuncInfo, node: ast.Call,
                   params: Set[str], scope: Tuple[str, ...],
                   cls: Optional[str], effects: bool) -> None:
        f = node.func
        chain = _attr_chain(f)
        wrapper = _is_trace_wrapper(f)
        if wrapper and node.args:
            arg = _unwrap_partial(node.args[0])
            if isinstance(arg, ast.Lambda):
                target = self._find_lambda(arg)
            else:
                target = None
            self.rule.root_args.append(
                (self.module, scope, cls, arg, target, wrapper))
        if effects:
            self._scan_effects(info, node, chain, params)
        if isinstance(f, ast.Name):
            info.calls.append(("name", f.id))
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self":
                info.calls.append(("self", f.attr))
            else:
                info.calls.append(("mod", f.value.id, f.attr))

    def _find_lambda(self, node: ast.Lambda) -> Optional[_FuncInfo]:
        for info in self.rule.funcs.values():
            if info.node is node:
                return info
        return None

    def _scan_effects(self, info: _FuncInfo, node: ast.Call,
                      chain: List[str], params: Set[str]) -> None:
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in EFFECT_NAME_CALLS:
                info.effects.append(
                    (node.lineno, f"{f.id}() is a host effect"))
            # NOTE: float(x)/int(x) on a *traced* value is also a host
            # sync, but statically indistinguishable from the legal (and
            # pervasive) trace-time coercion of static python config
            # (eps, scale, dropout_p) — .item() below is the decidable
            # spelling of that bug class
            return
        if not chain:
            return
        if chain[0] == "time":
            info.effects.append(
                (node.lineno,
                 f"{'.'.join(chain)}() reads the host clock (frozen at "
                 f"trace time inside traced code)"))
        elif chain[-1] in EFFECT_ATTR_CALLS:
            info.effects.append(
                (node.lineno, f".{chain[-1]}() is a host effect"))
        elif chain[-1] in METRIC_ATTR_CALLS:
            info.effects.append(
                (node.lineno,
                 f".{chain[-1]}() mutates a host-side metrics sink "
                 f"(fires once per trace, not per step — record around "
                 f"the jitted call, never inside it)"))
        elif "debug" in chain[:-1] and \
                chain[-1] in ("print", "callback", "breakpoint"):
            info.effects.append(
                (node.lineno,
                 f"{'.'.join(chain)}() is a callback escape (the "
                 f"no-callback-jaxpr invariant)"))


@register
class TracePurityRule(Rule):
    RULE_ID = "APX001"
    SUMMARY = ("no host effects (clocks, events, prints, file I/O, "
               ".item(), callbacks) reachable from traced code")

    SCOPE = "apex_tpu"

    def check(self, ctx: LintContext) -> Iterator[Violation]:
        self.funcs: Dict[Tuple[str, ...], _FuncInfo] = {}
        self.by_module_scope: Dict[Tuple, Dict[str, _FuncInfo]] = {}
        self.methods: Dict[Tuple[str, str], Dict[str, _FuncInfo]] = {}
        # (module, scope, class, arg_expr, pre-resolved lambda, wrapper)
        self.root_args: List[Tuple] = []
        self.module_index: Dict[str, _ModuleIndex] = {}

        for sf in ctx.iter_files(under=self.SCOPE):
            if sf.tree is None:
                continue
            module = os.path.splitext(sf.path)[0].replace(os.sep, ".")
            if module.endswith(".__init__"):
                module = module[:-len(".__init__")]
            indexer = _Indexer(self, sf, module)
            indexer.index(sf.tree)
            self.module_index[module] = indexer.idx

        roots: List[_FuncInfo] = [i for i in self.funcs.values()
                                  if i.is_root]
        for module, scope, cls, arg, lam, wrapper in self.root_args:
            info = lam if lam is not None else \
                self._resolve_expr(module, scope, cls, arg)
            if info is not None and not info.is_root:
                info.is_root = True
                info.root_why = wrapper
                roots.append(info)

        # DFS reachability with provenance paths for the report
        seen: Dict[Tuple[str, ...], List[str]] = {}
        frontier: List[_FuncInfo] = []
        for r in sorted(roots, key=lambda i: i.key):
            if r.key not in seen:
                seen[r.key] = [f"{r.name}[{r.root_why}]"]
                frontier.append(r)
        while frontier:
            cur = frontier.pop()
            path = seen[cur.key]
            for ref in self._edges(cur):
                if ref.name in BOUNDARY_FUNCS:
                    continue
                if ref.key not in seen:
                    seen[ref.key] = path + [ref.name]
                    frontier.append(ref)

        reported: Set[Tuple[str, int]] = set()
        for key in sorted(seen):
            info = self.funcs.get(key)
            if info is None:
                continue
            via = " -> ".join(seen[key])
            for lineno, desc in info.effects:
                site = (info.sf.path, lineno)
                if site in reported:
                    continue
                reported.add(site)
                yield self.violation(
                    info.sf, lineno,
                    f"{desc}; reachable from traced code via {via}")

    # ---- resolution -----------------------------------------------------
    def _edges(self, info: _FuncInfo) -> List[_FuncInfo]:
        out: List[_FuncInfo] = []
        inner_scope = (info.module, info.scope + (info.name,))
        for name, nested in self.by_module_scope.get(inner_scope,
                                                     {}).items():
            # a nested def referenced by name in the body is assumed
            # called (or passed onward into traced code)
            if name in info.loads:
                out.append(nested)
        for ref in info.calls:
            target: Optional[_FuncInfo] = None
            if ref[0] == "name":
                target = self._resolve_name(
                    info.module, info.scope + (info.name,), ref[1])
            elif ref[0] == "self" and info.class_name is not None:
                target = self.methods.get(
                    (info.module, info.class_name), {}).get(ref[1])
            elif ref[0] == "mod":
                target = self._resolve_attr(info.module, ref[1], ref[2])
            if target is not None:
                out.append(target)
        return out

    def _resolve_name(self, module: str, scope: Tuple[str, ...],
                      name: str) -> Optional[_FuncInfo]:
        """Lexical: innermost enclosing scope outward to module level,
        then from-imports within apex_tpu."""
        for i in range(len(scope), -1, -1):
            hit = self.by_module_scope.get((module, scope[:i]),
                                           {}).get(name)
            if hit is not None:
                return hit
        imp = self.module_index.get(module)
        if imp is not None:
            ref = imp.imports.get(name)
            if ref is not None and ref[0] == "from":
                return self.by_module_scope.get((ref[1], ()),
                                                {}).get(ref[2])
        return None

    def _resolve_attr(self, module: str, alias: str,
                      attr: str) -> Optional[_FuncInfo]:
        imp = self.module_index.get(module)
        if imp is None:
            return None
        ref = imp.imports.get(alias)
        if ref is None:
            return None
        if ref[0] == "module":
            return self.by_module_scope.get((ref[1], ()), {}).get(attr)
        # from-import of a submodule: `from apex_tpu.serve import kv_cache`
        sub = f"{ref[1]}.{ref[2]}"
        return self.by_module_scope.get((sub, ()), {}).get(attr)

    def _resolve_expr(self, module: str, scope: Tuple[str, ...],
                      cls: Optional[str], arg: ast.AST
                      ) -> Optional[_FuncInfo]:
        """Resolve a callable expression passed to a trace wrapper."""
        if isinstance(arg, ast.Name):
            return self._resolve_name(module, scope, arg.id)
        if isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name) and \
                arg.value.id == "self" and cls is not None:
            return self.methods.get((module, cls), {}).get(arg.attr)
        return None
