"""APX003 — every literal event name must be registered in the schema.

``apex_tpu.monitor.goodput`` owns THE event-name schema (``STALL_EVENTS``
| ``COUNTED_EVENTS`` | ``INFO_EVENTS``): an event published under an
unregistered name reaches no monitoring consumer — the goodput ledger
drops it, dashboards never chart it, and the flight recorder can't be
grepped for it. This rule walks the package AST for every call to
``publish_event`` / ``structured_warning`` whose event argument is a
string literal and fails on names outside the schema.

The schema tables are read from goodput.py's **AST** (``literal_eval`` on
the three assignments), not by importing ``apex_tpu`` — the linter must
run in environments with no jax backend, and a schema file broken enough
to not literal-eval should fail the lint loudly anyway.

This is the one source of truth for event-name auditing:
``tests/test_monitor.py::test_repo_wide_event_schema_audit`` delegates
here instead of keeping its own regex scan.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, Optional, Set

from ..core import LintContext, Rule, Violation, register

PUBLISH_FUNCS = ("publish_event", "structured_warning")
SCHEMA_PATH = os.path.join("apex_tpu", "monitor", "goodput.py")
SCHEMA_TABLES = ("STALL_EVENTS", "COUNTED_EVENTS", "INFO_EVENTS")


def load_event_schema(root: str) -> Set[str]:
    """The registered event names, extracted from goodput.py's AST."""
    path = os.path.join(root, SCHEMA_PATH)
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    names: Set[str] = set()
    seen = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name) or \
                target.id not in SCHEMA_TABLES:
            continue
        value = ast.literal_eval(node.value)
        seen.add(target.id)
        names |= set(value)  # dict → keys; tuple/list → elements
    missing = set(SCHEMA_TABLES) - seen
    if missing:
        raise ValueError(
            f"{SCHEMA_PATH}: schema table(s) {sorted(missing)} not found "
            f"as literal assignments — APX003 cannot audit against them")
    return names


def _event_name_arg(node: ast.Call) -> Optional[ast.Constant]:
    """The literal event-name argument, if this is a publish call."""
    fname = None
    if isinstance(node.func, ast.Name):
        fname = node.func.id
    elif isinstance(node.func, ast.Attribute):
        fname = node.func.attr
    if fname not in PUBLISH_FUNCS:
        return None
    if node.args and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "event" and isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            return kw.value
    return None


@register
class EventSchemaRule(Rule):
    RULE_ID = "APX003"
    SUMMARY = ("literal publish_event/structured_warning names must be "
               "registered in apex_tpu.monitor.goodput's event schema")

    # the schema's own module publishes nothing; scope is the package
    SCOPE = "apex_tpu"

    def check(self, ctx: LintContext) -> Iterator[Violation]:
        try:
            schema = load_event_schema(ctx.root)
        except (OSError, ValueError, SyntaxError) as e:
            # no schema file (fixture trees) → nothing to audit against
            for sf in ctx.iter_files(under=self.SCOPE):
                if sf.path == SCHEMA_PATH.replace("/", os.sep):
                    yield self.violation(sf, 1, f"schema unreadable: {e}")
            return
        for sf in ctx.iter_files(under=self.SCOPE):
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                arg = _event_name_arg(node)
                if arg is not None and arg.value not in schema:
                    yield self.violation(
                        sf, node.lineno,
                        f"event {arg.value!r} is not registered in the "
                        f"goodput schema (add it to STALL_EVENTS/"
                        f"COUNTED_EVENTS/INFO_EVENTS in "
                        f"apex_tpu/monitor/goodput.py)")
