"""``apex-tpu-lint`` / ``python -m tools.apexlint`` entry point.

Exit-code contract (what CI keys on):

- ``0`` — no active violations (justified suppressions are fine),
- ``1`` — at least one violation (including APX000 unjustified-suppression
  and unparseable files),
- ``2`` — usage error (unknown rule id, bad path).

Default scan set is ``apex_tpu/`` + ``tools/`` under the repo root; pass
explicit files/directories to narrow it (fixture tests do).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .core import REPO_ROOT, get_rules, run_lint
from .reporters import report_json, report_text


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="apex-tpu-lint",
        description="AST-based invariant linter for apex_tpu "
                    "(see docs/static-analysis.md)")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to scan (default: "
                             "apex_tpu/ and tools/ under --root)")
    parser.add_argument("--root", default=REPO_ROOT,
                        help="repo root (default: autodetected)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit 0")
    args = parser.parse_args(argv)

    only = ([r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules else None)
    try:
        if args.list_rules:
            for rule in get_rules(only):
                scope = getattr(rule, "SCOPE", None)
                where = f"[{scope}/]" if scope else "[all files]"
                print(f"{rule.RULE_ID}  {where}  {rule.SUMMARY}")
            return 0
        active, suppressed, ctx = run_lint(
            root=args.root, paths=args.paths or None, only=only)
    except (KeyError, OSError) as e:
        print(f"apex-tpu-lint: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        report_json(active, suppressed, ctx, get_rules(only), sys.stdout)
    else:
        report_text(active, suppressed, ctx, sys.stdout)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
