#!/usr/bin/env python
"""Bench regression gate: compare a fresh capture against a committed
baseline and exit nonzero on regression — perf claims become CI-checkable.

Usage::

    python tools/check_regression.py CURRENT BASELINE \
        [--tolerance 0.10] [--warmup 1] [--metric NAME ...]
    python tools/check_regression.py CURRENT --suite BENCH_BASELINE.json \
        [--kernels fused_adam_1b,layer_norm] [--tolerance 0.10]

The second form is the per-kernel perf gate: ``--suite`` names the
committed suite-format baseline (``apex-tpu-bench --kernels ...
--emit-baseline``), results are grouped and summarized per kernel entry,
and ``--kernels`` restricts the gate to a subset of entries (a fresh
subset capture then gates only what it measured). CPU-interpret numbers
gate CI; real-chip numbers are checked in from bench runs
(docs/performance.md "Autotuning and the perf baseline gate").

``CURRENT`` and ``BASELINE`` each accept either format:

- a **telemetry JSONL** (``apex-tpu-bench --telemetry-jsonl``, or an
  example run with ``--telemetry-jsonl``): per-step metric rows are
  aggregated to their **median** over the steady state (the first
  ``--warmup`` rows dropped; medians shrug off one straggler step), event
  rows are ignored;
- a **bench suite JSON** (``BENCH_SUITE.json`` / ``BENCH_*.json`` shape):
  each sub-bench contributes its headline ``value`` (named by the entry
  key) plus numeric detail fields as ``<entry>.<field>``;
- a **metrics snapshot** (``schema: "apex_tpu.metrics/v1"`` — from
  ``--metrics-snapshot``, a ``/metrics.json`` scrape, or a
  ``tools/metrics_merge.py`` fleet merge): counter families contribute
  their cross-series totals, seconds-valued histograms contribute
  nearest-rank ``<name>_p50_ms``/``<name>_p99_ms`` quantiles computed
  over the merged buckets with the snapshot's own bucket geometry, and
  the derived failure fractions ``shed_frac``/``deadline_miss_frac``
  gate lower-is-better — so the serve bench and a live scrape produce
  comparably gateable artifacts. Gauges are skipped (a point-in-time
  level at whatever instant the snapshot was cut is not a perf claim).

Only metrics present on BOTH sides are compared (each skip is reported).
Direction is inferred from the name/unit: ``*_ms``/``*_s``/unit ``ms`` are
lower-is-better; throughputs and fractions (``tokens_per_s``, ``mfu``,
``hbm_frac``, ``vs_baseline``, ...) are higher-is-better. A metric
regresses when it is worse than baseline by more than ``--tolerance``
(relative). Harness-noise fields (``bench_wall_s``, ``t``, wall stamps)
are excluded.

Suite captures carry provenance stamps (``device_kind``,
``interpret_mode``, ``git``, ``captured`` — ``apex-tpu-bench`` writes
them): when capture and baseline device kinds differ, the gate prints a
LOUD warning (a CPU-smoke capture must not gate TPU numbers), and
``--fail-device-mismatch`` makes it exit 1.

Exit status: 0 all compared metrics within tolerance, 1 any regression
(or a device mismatch under ``--fail-device-mismatch``), 2 usage error /
nothing comparable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# never compared: harness/bookkeeping and training-health values, not perf
# (steps/slots are workload configuration — a shorter capture is not a
# regression)
EXCLUDED = {"step", "t", "bench_wall_s", "fetch_floor_ms", "found_inf",
            "loss_scale", "grad_norm", "param_norm", "update_norm",
            "steps", "slots"}
_LOWER_SUFFIXES = ("_ms", "_s", "_us", "_latency")
# serving latency names beat the generic rules ("ttft" carries no unit
# suffix when reported in seconds; p50/p99 quantile columns are latencies).
# Overload SLO counters are failure rates: more shed/rejected/expired
# requests is strictly worse — without the hint "rejected" would default
# to higher-is-better and a shedding regression would gate as a win.
# Fleet resilience counters are the same family: a 0 -> N failover (or
# hedge, or replica-death) storm in a capture is a regression the gate
# must catch, never a win.
_LOWER_HINTS = ("ttft", "latency", "_p50", "_p99", "queue_wait",
                "shed_rate", "rejected", "deadline_exceeded", "evicted",
                "failover", "hedge_fired", "replica_dead",
                # fleet tracing (PR 13): every promoted journey is a
                # bad-outcome request the tail capture had to rescue —
                # a 0 -> N promotion storm gates as a regression
                "trace_promoted",
                # production trainer (PR 14): supervisor restarts,
                # preemption drains, replayed steps, and recompiles are
                # lost work — a 0 -> N (or 1 -> N) storm in a chaos
                # capture is a regression the gate must catch, never a
                # win ("restarts" deliberately plural: the fleet's
                # "replica_restarted" counter keeps its own direction)
                "restarts", "preempt_drains", "steps_retried",
                "recompile",
                # disaggregated serving (PR 16): refused handoffs are
                # certification failures (corrupt/torn page streams) and
                # autoscale up/down counts are control-loop churn — a
                # 0 -> N refusal storm or a flapping autoscaler gates
                # off a zero baseline, never reads as neutral
                "handoff_refused", "autoscale",
                # cost-ledger families (PR 17): static per-step work.
                # These are DEVICE-INDEPENDENT — a fusion that claims a
                # win must move flops/bytes/op-count, and a regression
                # here is real work growth no wall-clock noise excuses
                "flops_per_token", "hbm_bytes_per_token", "ops_total",
                # topology-portable checkpoints (PR 19): a quarantine
                # storm (bit-rotted blobs) or unexpected reshard churn
                # on restore gates off a zero baseline
                "ckpt_quarantined", "topology_restored",
                # block-scale KV quantization (PR 20): the perplexity
                # delta of a quantized engine vs its fp32 reference —
                # quality erosion, strictly worse as it grows; and the
                # codec-mismatch fallback counter (each one is a
                # refused handoff that re-prefilled locally)
                "quant_ppl_delta", "quant_fallback")
# throughput/utilization names trump the time suffixes ("tokens_per_s"
# ends in "_s" but is a rate). "hit_rate" (paged-KV prefix cache) must
# beat the "_rate" lower-hint family: fewer hits means more repeated
# prefill, which is strictly worse.
_HIGHER_HINTS = ("_per_s", "per_sec", "_frac", "mfu", "tflops",
                 "vs_baseline", "goodput", "imgs", "tokens", "seqs",
                 "hit_rate",
                 # cost-ledger roofline bound (PR 17): a predicted-MFU
                 # drop means the step moved toward memory-bound — worse
                 # ("mfu" already matches, listed for the explicit record)
                 "predicted_mfu",
                 # speculative decoding (PR 18): tokens committed per
                 # verify step and the draft acceptance fraction — both
                 # collapse to the one-token floor when speculation stops
                 # paying, so a drop is a strict regression
                 # ("tokens"/"_per_s" already match the throughput names;
                 # listed for the explicit record)
                 "accepted_tokens_per_step", "accept_rate",
                 # block-scale KV quantization (PR 20): resident tokens
                 # per KV-cache HBM byte — THE capacity win a quantized
                 # pool exists for; a drop means the pool got more
                 # expensive per token ("tokens" already matches, listed
                 # for the explicit record)
                 "resident_tokens_per_hbm_byte")
# failure fractions beat the generic "_frac" higher family (the mirror
# of the hit_rate-vs-_rate precedent): a snapshot's shed_frac or
# deadline_miss_frac going UP is strictly worse — without the override
# "_frac" would gate more shedding as a win
_LOWER_OVERRIDES = ("shed_frac", "miss_frac", "fail_frac")


def lower_is_better(name: str, unit: Optional[str] = None) -> bool:
    """Direction-aware comparison: serve metrics follow the same rules —
    ``serve_decode`` (unit tokens_per_s) is higher-is-better while its
    ``p50_ms``/``p99_ms``/``ttft_ms`` detail latencies are lower-is-better.
    """
    lname = name.lower()
    if any(h in lname for h in _LOWER_OVERRIDES):
        return True
    if unit and ("per_s" in unit or unit.endswith("/s")):
        return False
    if any(h in lname for h in _HIGHER_HINTS):
        return False
    if unit == "ms" or any(h in lname for h in _LOWER_HINTS):
        return True
    return lname.endswith(_LOWER_SUFFIXES) or lname.endswith("loss")


def median(vals: List[float]) -> float:
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def metrics_from_jsonl(lines: List[dict], warmup: int) -> Dict[str, Tuple[float, Optional[str]]]:
    rows = [r for r in lines if "event" not in r]
    rows = rows[warmup:] if len(rows) > warmup else rows
    out: Dict[str, Tuple[float, Optional[str]]] = {}
    if not rows:
        return out
    keys = set().union(*(r.keys() for r in rows)) - EXCLUDED
    for k in sorted(keys):
        vals = [float(r[k]) for r in rows
                if isinstance(r.get(k), (int, float))
                and not isinstance(r.get(k), bool)]
        if vals:
            out[k] = (median(vals), None)
    return out


METRICS_SNAPSHOT_SCHEMA = "apex_tpu.metrics/v1"
COST_LEDGER_SCHEMA = "apex_tpu.cost_ledger/v1"

_EXPORT_MOD = None
_COSTS_MOD = None


def _export_module():
    """Load ``apex_tpu/monitor/export.py`` by file path — the module is
    stdlib-only at import time for exactly this kind of caller (the gate
    must run on machines with no jax; importing the ``apex_tpu`` package
    would pull it). Same pattern as ``tools/metrics_merge.py``, and the
    reason there is exactly ONE copy of the nearest-rank quantile rule:
    a second spelling here could silently diverge from the exporter's
    own quantiles."""
    global _EXPORT_MOD
    if _EXPORT_MOD is None:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "apex_tpu", "monitor", "export.py")
        spec = importlib.util.spec_from_file_location(
            "_apex_tpu_metrics_export_gate", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _EXPORT_MOD = mod
    return _EXPORT_MOD


def _costs_module():
    """Load ``apex_tpu/monitor/costs.py`` by file path (the
    ``_export_module`` pattern): import-time stdlib-only by contract, so
    the gate keeps running jax-free, and the ONE spelling of the
    ledger's gate-metric / incomparability rules lives in costs.py —
    shared with ``tools/cost_diff.py`` and ``Engine.cost_ledger()``."""
    global _COSTS_MOD
    if _COSTS_MOD is None:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "apex_tpu", "monitor", "costs.py")
        spec = importlib.util.spec_from_file_location(
            "_apex_tpu_costs_gate", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _COSTS_MOD = mod
    return _COSTS_MOD


def metrics_from_ledger(doc: dict) -> Dict[str, Tuple[float, Optional[str]]]:
    """Gateable metrics from an ``apex_tpu.cost_ledger/v1`` document,
    prefixed ``cost_ledger.`` so the per-kernel summary groups them as
    one entry. The families are the device-independent ones
    (``decode_flops_per_token`` / ``decode_hbm_bytes_per_token`` /
    ``decode_ops_total``, per-phase splits) plus the roofline
    projections when the chip spec is a gating one (never the cpu
    fallback) — ``costs.ledger_gate_metrics`` owns the selection."""
    gm = _costs_module().ledger_gate_metrics(doc)
    return {f"cost_ledger.{k}": (float(v), None)
            for k, v in sorted(gm.items())}


def _snapshot_quantile(buckets: Dict[int, int], count: int, p: float,
                       lo: float, growth: float) -> float:
    """Nearest-rank quantile over merged log-bucket counts, using the
    SNAPSHOT'S own bucket geometry (never this tool's idea of it):
    delegates to THE quantile rule in monitor.export."""
    return _export_module().histogram_quantile(
        buckets, count, p, lo=lo, growth=growth)


def metrics_from_snapshot(doc: dict) -> Dict[str, Tuple[float, Optional[str]]]:
    """Gateable metrics from an ``apex_tpu.metrics/v1`` snapshot:
    counter totals (summed across label series), histogram-derived
    ``_p50_ms``/``_p99_ms`` quantiles for seconds-valued families, and
    the derived ``shed_frac``/``deadline_miss_frac`` failure fractions.
    Gauges are point-in-time levels, not perf claims — skipped."""
    out: Dict[str, Tuple[float, Optional[str]]] = {}
    counters: Dict[str, float] = {}
    for name, fam in doc.get("metrics", {}).items():
        if not isinstance(fam, dict):
            continue
        series = fam.get("series", [])
        if fam.get("type") == "counter":
            total = float(sum(s.get("value", 0.0) for s in series))
            counters[name] = total
            out[name] = (total, None)
        elif fam.get("type") == "histogram":
            # ONLY seconds-valued families (the repo's *_seconds naming
            # contract) become _p50_ms/_p99_ms: scaling a token-count or
            # batch-size distribution by 1e3 and gating it as a
            # forced-lower-is-better latency would be silently wrong in
            # both value and direction
            if not name.endswith("_seconds"):
                continue
            buckets: Dict[int, int] = {}
            count = 0
            for s in series:
                count += int(s.get("count", 0))
                for idx, n in s.get("buckets", {}).items():
                    buckets[int(idx)] = buckets.get(int(idx), 0) + int(n)
            if not count:
                continue
            base = name
            if base.startswith("serve_"):
                base = base[len("serve_"):]
            base = base[:-len("_seconds")]
            lo = float(fam.get("lo", 1e-6))
            growth = float(fam.get("growth", 2.0 ** 0.125))
            for p, tag in ((0.50, "p50"), (0.99, "p99")):
                q = _snapshot_quantile(buckets, count, p, lo, growth)
                out[f"{base}_{tag}_ms"] = (q * 1e3, "ms")
    submitted = counters.get("serve_requests_submitted_total", 0.0)
    if submitted > 0:
        out["shed_frac"] = (
            counters.get("serve_requests_rejected_total", 0.0) / submitted,
            None)
        out["deadline_miss_frac"] = (
            counters.get("serve_deadline_exceeded_total", 0.0) / submitted,
            None)
    return out


def metrics_from_suite(suite: dict) -> Dict[str, Tuple[float, Optional[str]]]:
    out: Dict[str, Tuple[float, Optional[str]]] = {}
    for name, entry in suite.items():
        if not isinstance(entry, dict) or "error" in entry \
                or "value" not in entry:
            continue
        unit = entry.get("unit")
        out[name] = (float(entry["value"]), unit)
        for k, v in entry.items():
            if k in ("value", "metric", "unit") or k in EXCLUDED:
                continue
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"{name}.{k}"] = (float(v), None)
    return out


def load_metrics(path: str, warmup: int) -> Dict[str, Tuple[float, Optional[str]]]:
    """Sniff the file format (JSONL vs one JSON document) and extract
    ``{metric_name: (value, unit|None)}``."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            if doc.get("schema") == METRICS_SNAPSHOT_SCHEMA:
                return metrics_from_snapshot(doc)
            if doc.get("schema") == COST_LEDGER_SCHEMA:
                return metrics_from_ledger(doc)
            # a one-row telemetry JSONL is also a single JSON dict —
            # disambiguate by shape (suite entries are dicts with "value")
            is_suite = any(isinstance(v, dict) and "value" in v
                           for v in doc.values())
            if not is_suite and "step" in doc:
                return metrics_from_jsonl([doc], warmup=0)
            return metrics_from_suite(doc)
    except ValueError:
        pass
    lines = []
    for line in text.splitlines():
        line = line.strip()
        if line:
            lines.append(json.loads(line))
    return metrics_from_jsonl(lines, warmup)


def capture_provenance(path: str) -> Dict[str, object]:
    """Best-effort provenance fields from a suite-format capture
    (``device_kind``, ``interpret_mode``, ``chip``, ``backend``, ``git``).
    Telemetry JSONLs and old baselines without the stamps return ``{}``."""
    try:
        with open(path) as f:
            doc = json.loads(f.read())
    except (ValueError, OSError):
        return {}
    if not isinstance(doc, dict):
        return {}
    if doc.get("schema") in (METRICS_SNAPSHOT_SCHEMA, COST_LEDGER_SCHEMA):
        # snapshots AND cost ledgers stamp provenance under "meta"
        # (apex-tpu-bench passes capture_provenance() through), so the
        # device-mismatch guard covers them against any other format
        doc = doc.get("meta") or {}
        if not isinstance(doc, dict):
            return {}
    return {k: doc[k] for k in ("device_kind", "interpret_mode", "chip",
                                "backend", "git", "captured")
            if k in doc}


def device_kinds(cur_prov: Dict[str, object],
                 base_prov: Dict[str, object]
                 ) -> Tuple[Optional[str], Optional[str]]:
    """The comparable device identities of the two captures.

    Compared like-for-like: the stamped ``device_kind`` when BOTH sides
    carry it, else the legacy ``chip`` field when both carry that
    (``cpu-smoke`` vs a TPU generation). Mixing vocabularies — a new
    capture's ``device_kind: "cpu"`` against a legacy baseline's ``chip:
    "cpu-smoke"`` — would flag identical hardware, so a key present on
    only one side is never compared against the other key."""
    for key in ("device_kind", "chip"):
        cur, base = cur_prov.get(key), base_prov.get(key)
        if cur is not None and base is not None:
            return str(cur), str(base)
    return None, None


def check_device_kinds(current_path: str, baseline_path: str,
                       fail_on_mismatch: bool) -> bool:
    """Warn LOUDLY (optionally fail) when capture and baseline come from
    different device kinds OR interpret modes — a CPU-smoke/interpret
    capture gating as if it were real-chip numbers (or vice versa) is the
    standing confusion this ends. Returns True when the mismatch should
    fail the gate."""
    cur_prov = capture_provenance(current_path)
    base_prov = capture_provenance(baseline_path)
    cur, base = device_kinds(cur_prov, base_prov)
    mismatch = None
    if cur is not None and base is not None and cur != base:
        mismatch = f"current capture is {cur!r}, baseline is {base!r}"
    else:
        # same chip is not enough: interpret-mode Pallas numbers on a TPU
        # host are still not real-chip numbers
        cur_im = cur_prov.get("interpret_mode")
        base_im = base_prov.get("interpret_mode")
        if cur_im is not None and base_im is not None \
                and bool(cur_im) != bool(base_im):
            mismatch = (f"current capture interpret_mode={bool(cur_im)}, "
                        f"baseline interpret_mode={bool(base_im)}")
    if mismatch is None:
        return False
    print("=" * 72, file=sys.stderr)
    print(f"WARNING: device-kind mismatch — {mismatch}.\n"
          f"These numbers are NOT comparable: an interpret-mode/CPU-smoke "
          f"capture must not gate real-chip numbers (or vice versa). "
          f"Re-capture on the baseline's device kind, or refresh the "
          f"baseline. Pass --fail-device-mismatch to make this fatal.",
          file=sys.stderr)
    print("=" * 72, file=sys.stderr)
    return fail_on_mismatch


# workload axes that make two captures of one entry INCOMPARABLE rather
# than merely differently-shaped: a tensor-parallel capture's tokens/s
# measures a sharded decode step (collective latency included) and its
# per-rank HBM budget is 1/tp of the pool — gating it against a
# single-chip baseline would be wrong in BOTH directions, so the gate
# REFUSES the entry instead of comparing it. The sync mode is the same
# kind of axis: a relaxed-sync capture runs half the collectives and
# row-parallel matmuls — its tokens/s must never gate against an
# exact-mode capture as a clean win. The dict value is the default for
# captures that predate the axis (old baselines carry no "tp" key and
# are single-chip by construction; tp_sync is stamped None off-mesh).
# Disaggregation is a third such axis: a disaggregated capture spends
# decode-replica capacity on migrated pages and routes prefill work to
# dedicated replicas — its latency/throughput must never gate against a
# unified capture (roles None = unified; old captures predate the axis).
# Speculative decoding (PR 18) is a fourth: a spec capture commits
# multi-token verify steps — its tokens/s rides acceptance luck and its
# step time carries draft_len + 1 positions of compute, so neither
# direction compares against a one-token capture (or across draft
# widths / decode policies). Missing keys = speculation off / legacy
# greedy, the pre-PR-18 default.
INCOMPARABLE_WORKLOAD_KEYS = {"tp": 1, "tp_sync": None,
                              "disagg": False, "roles": None,
                              "diurnal": False,
                              "spec": False, "draft_len": 0,
                              "decode_policy": None,
                              # block-scale KV quantization (PR 20): a
                              # quantized capture's capacity/latency
                              # numbers must never gate against an fp32
                              # baseline (or across codecs/blocks).
                              # Missing keys = unquantized, the
                              # pre-quant default.
                              "kv_quant": None, "quant_block": 0}


def incomparable_entries(cur_doc: dict, base_doc: dict) -> Dict[str, str]:
    """Suite entries whose nested ``workload`` provenance differs on an
    incomparability axis — ``{entry_name: reason}``. Entries without
    workload dicts on both sides (kernel benches, old formats) are never
    refused here; absence of the axis means its default."""
    out: Dict[str, str] = {}
    for name, cur in cur_doc.items():
        base = base_doc.get(name)
        if not isinstance(cur, dict) or not isinstance(base, dict):
            continue
        wc, wb = cur.get("workload"), base.get("workload")
        if not isinstance(wc, dict) or not isinstance(wb, dict):
            continue
        for key, default in INCOMPARABLE_WORKLOAD_KEYS.items():
            a, b = wc.get(key, default), wb.get(key, default)
            if a != b:
                out[name] = (f"workload.{key}={a} vs baseline "
                             f"workload.{key}={b}")
                break    # first differing axis names the refusal
    return out


def _ledger_doc(path: str) -> Optional[dict]:
    """The raw cost-ledger document at ``path`` (None for everything
    else)."""
    try:
        with open(path) as f:
            doc = json.loads(f.read())
    except (ValueError, OSError):
        return None
    if isinstance(doc, dict) and doc.get("schema") == COST_LEDGER_SCHEMA:
        return doc
    return None


def _suite_doc(path: str) -> Optional[dict]:
    """The raw suite-format document at ``path`` (None for JSONLs,
    snapshots, and anything else ``incomparable_entries`` cannot read)."""
    try:
        with open(path) as f:
            doc = json.loads(f.read())
    except (ValueError, OSError):
        return None
    if not isinstance(doc, dict) \
            or doc.get("schema") == METRICS_SNAPSHOT_SCHEMA:
        return None
    if any(isinstance(v, dict) and "value" in v for v in doc.values()):
        return doc
    return None


def compare(current: Dict[str, Tuple[float, Optional[str]]],
            baseline: Dict[str, Tuple[float, Optional[str]]],
            tolerance: float, only: Optional[List[str]] = None) -> Tuple[List[dict], List[str]]:
    """Returns ``(results, skipped)``; each result row carries the verdict."""
    results: List[dict] = []
    skipped: List[str] = []
    names = sorted(set(current) | set(baseline))
    if only:
        names = [n for n in names if n in only]
    for name in names:
        if name not in current or name not in baseline:
            skipped.append(name)
            continue
        cur, unit = current[name]
        base, base_unit = baseline[name]
        lower = lower_is_better(name, unit or base_unit)
        if base == 0:
            # no relative ratio exists — but for a lower-is-better
            # failure counter (rejected, deadline_exceeded, shed_rate,
            # a latency) a 0 -> N move is the regression the gate
            # exists to catch: skipping it would let a healthy-baseline
            # capture start shedding silently. 0 -> 0 is a clean pass;
            # higher-is-better metrics with a zero baseline stay
            # skipped (any value is an improvement of unknowable size).
            if lower:
                results.append({
                    "metric": name, "baseline": base, "current": cur,
                    "ratio": float("inf") if cur > 0 else 1.0,
                    "direction": "lower",
                    "regressed": cur > 0,
                })
            else:
                skipped.append(name)
            continue
        ratio = cur / base
        worse = ratio - 1.0 if lower else 1.0 - ratio
        results.append({
            "metric": name, "baseline": base, "current": cur,
            "ratio": round(ratio, 4),
            "direction": "lower" if lower else "higher",
            "regressed": worse > tolerance,
        })
    return results, skipped


def filter_kernels(metrics: Dict[str, Tuple[float, Optional[str]]],
                   kernels: List[str]) -> Dict[str, Tuple[float, Optional[str]]]:
    """Keep only metrics belonging to the named suite entries (the entry
    headline ``name`` plus its ``name.<field>`` details)."""
    keep = set(kernels)
    return {name: v for name, v in metrics.items()
            if name in keep or name.split(".", 1)[0] in keep}


def summarize_per_kernel(results: List[dict]) -> Dict[str, dict]:
    """Group comparison rows by suite entry (prefix before the first dot)
    and report a per-kernel verdict."""
    groups: Dict[str, dict] = {}
    for r in results:
        kernel = r["metric"].split(".", 1)[0]
        g = groups.setdefault(kernel, {"compared": 0, "regressions": 0})
        g["compared"] += 1
        g["regressions"] += int(r["regressed"])
    return groups


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="compare a fresh bench capture against a baseline")
    ap.add_argument("current", help="fresh telemetry JSONL or suite JSON")
    ap.add_argument("baseline", nargs="?", default=None,
                    help="committed BENCH_*.json or JSONL (or use --suite)")
    ap.add_argument("--suite", default=None,
                    help="committed per-kernel suite baseline "
                         "(BENCH_BASELINE.json); results are grouped per "
                         "kernel entry")
    ap.add_argument("--kernels", default=None,
                    help="comma-separated suite entries to gate "
                         "(e.g. fused_adam_1b,layer_norm)")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed relative slowdown (default 0.10 = 10%%)")
    ap.add_argument("--warmup", type=int, default=1,
                    help="leading JSONL rows to drop (compile step)")
    ap.add_argument("--metric", action="append", default=None,
                    help="restrict the comparison to these metric names")
    ap.add_argument("--fail-device-mismatch", action="store_true",
                    help="exit 1 when capture and baseline device_kind "
                         "differ (default: loud warning only)")
    args = ap.parse_args(argv)

    if (args.baseline is None) == (args.suite is None):
        print("check_regression: pass exactly one of BASELINE or --suite",
              file=sys.stderr)
        return 2
    baseline_path = args.suite or args.baseline

    for path in (args.current, baseline_path):
        if not os.path.exists(path):
            print(f"check_regression: no such file: {path}",
                  file=sys.stderr)
            return 2
    try:
        current = load_metrics(args.current, args.warmup)
        baseline = load_metrics(baseline_path, args.warmup)
    except ValueError as e:
        print(f"check_regression: unparseable input: {e}", file=sys.stderr)
        return 2

    device_fail = check_device_kinds(args.current, baseline_path,
                                     args.fail_device_mismatch)

    if args.kernels:
        names = [k.strip() for k in args.kernels.split(",") if k.strip()]
        current = filter_kernels(current, names)
        baseline = filter_kernels(baseline, names)

    # comparability guard: entries whose workload provenance differs on
    # an incomparability axis (mesh shape) are REFUSED — dropped from
    # BOTH sides with a loud line, so e.g. a tp=2 capture never gates
    # its sharded tokens/s against a single-chip baseline (in either
    # direction)
    cur_doc, base_doc = _suite_doc(args.current), _suite_doc(baseline_path)
    if cur_doc is not None and base_doc is not None:
        for name, reason in sorted(
                incomparable_entries(cur_doc, base_doc).items()):
            print(f"INCOMPARABLE [{name}] {reason} — refusing to gate "
                  f"this entry (the two captures measure different "
                  f"serving pipelines)")
            current = {k: v for k, v in current.items()
                       if k != name and k.split(".", 1)[0] != name}
            baseline = {k: v for k, v in baseline.items()
                        if k != name and k.split(".", 1)[0] != name}

    # the same refusal discipline for cost ledgers: the incomparability
    # axes live in the ledger's own workload provenance (tp / tp_sync /
    # page_size / dtype / num_slots / max_len / chip_spec —
    # costs.LEDGER_INCOMPARABLE_KEYS), so a tp=2 or bf16 ledger never
    # gates its per-token work against a single-chip fp32 baseline
    cur_led, base_led = _ledger_doc(args.current), _ledger_doc(baseline_path)
    if cur_led is not None and base_led is not None:
        for reason in _costs_module().provenance_mismatch(cur_led,
                                                          base_led):
            print(f"INCOMPARABLE [cost_ledger] {reason} — refusing to "
                  f"gate this ledger (different workloads price "
                  f"different steps)")
            current = {k: v for k, v in current.items()
                       if not k.startswith("cost_ledger.")}
            baseline = {k: v for k, v in baseline.items()
                        if not k.startswith("cost_ledger.")}

    results, skipped = compare(current, baseline, args.tolerance,
                               args.metric)
    for name in skipped:
        print(f"SKIP       {name} (missing on one side or zero baseline)")
    for r in results:
        tag = "REGRESSION" if r["regressed"] else "OK"
        print(f"{tag:10s} {r['metric']}: baseline={r['baseline']:g} "
              f"current={r['current']:g} ratio={r['ratio']:g} "
              f"({r['direction']}-is-better)")
    regressions = [r for r in results if r["regressed"]]
    summary = {"compared": len(results),
               "regressions": len(regressions),
               "skipped": len(skipped),
               "tolerance": args.tolerance}
    if args.suite:
        per_kernel = summarize_per_kernel(results)
        for kernel in sorted(per_kernel):
            g = per_kernel[kernel]
            tag = "REGRESSION" if g["regressions"] else "OK"
            print(f"{tag:10s} [{kernel}] {g['compared']} compared, "
                  f"{g['regressions']} regressions")
        summary["per_kernel"] = per_kernel
    print(json.dumps(summary))
    if not results:
        print("check_regression: nothing comparable between the two "
              "captures", file=sys.stderr)
        return 2
    if device_fail:
        print("check_regression: failing on device-kind mismatch "
              "(--fail-device-mismatch)", file=sys.stderr)
        return 1
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
