# Makes ``tools`` importable so ``python -m tools.apexlint`` works from the
# repo root. The standalone scripts in this directory still run directly
# (``python tools/check_durability.py``) — being a package does not change
# script execution.
