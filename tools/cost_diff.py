#!/usr/bin/env python
"""Diff two compiled-step cost ledgers — the fusion sweep's before/after
oracle.

``apex-tpu-bench --serve --cost-ledger`` writes a provenance-stamped
``apex_tpu.cost_ledger/v1`` document (see ``apex_tpu/monitor/costs.py``
and docs/performance.md "Cost ledgers and roofline gating"). This tool
renders what moved between two of them: the derived per-token families,
then per executable the totals, the per-phase attribution
(``ln_qkv`` / ``attention`` / ``mlp`` / ``sampling`` / ``collective`` /
``other``), and every op family whose count changed. A real fusion must
move bytes/flops/op-count here — wall clock is not consulted.

Usage::

    python tools/cost_diff.py CURRENT.json BASELINE.json [--json]

Exit status: 0 diff printed (improvements and regressions alike — the
GATE is tools/check_regression.py; this is the attribution lens), 2 on
a provenance mismatch (different tp/tp_sync/page_size/dtype/slot
count/chip spec — the two ledgers price different steps, and a diff
would attribute the workload delta to code) or unreadable input.

This tool is **standalone**: it loads ``monitor/costs.py`` by file path
(the ``metrics_merge.py`` pattern), so it runs on a machine with no jax
installed.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_costs_module():
    """Load ``apex_tpu/monitor/costs.py`` WITHOUT importing the
    ``apex_tpu`` package (whose __init__ pulls jax): the module is
    deliberately stdlib-only at import time for exactly this caller."""
    path = os.path.join(_REPO, "apex_tpu", "monitor", "costs.py")
    spec = importlib.util.spec_from_file_location(
        "_apex_tpu_costs_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fmt(v) -> str:
    if isinstance(v, float) and v == int(v):
        v = int(v)
    return f"{v:g}" if isinstance(v, float) else str(v)


def _row_line(label: str, row: dict) -> str:
    ratio = f" ({row['ratio']:g}x)" if "ratio" in row else ""
    return (f"  {label:38s} {_fmt(row['baseline']):>14s} -> "
            f"{_fmt(row['current']):>14s}  delta={_fmt(row['delta'])}"
            f"{ratio}")


def render(diff: dict) -> List[str]:
    lines: List[str] = []
    if diff.get("derived"):
        lines.append("derived (per-token / roofline):")
        for k, row in diff["derived"].items():
            lines.append(_row_line(k, row))
    for name, ex in diff.get("executables", {}).items():
        lines.append(f"[{name}] totals:")
        for k, row in ex["total"].items():
            lines.append(_row_line(k, row))
        if ex["phases"]:
            lines.append(f"[{name}] per phase:")
            for ph, fields in ex["phases"].items():
                for k, row in fields.items():
                    lines.append(_row_line(f"{ph}.{k}", row))
        if ex["op_families"]:
            lines.append(f"[{name}] op families (changed only):")
            for fam, row in ex["op_families"].items():
                lines.append(_row_line(fam, row))
    if not lines:
        lines.append("cost_diff: ledgers are identical on every "
                     "compared family")
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two cost ledgers per phase/op-family "
                    "(exit 2 on provenance mismatch)")
    ap.add_argument("current", help="fresh cost ledger JSON")
    ap.add_argument("baseline", help="committed baseline ledger JSON")
    ap.add_argument("--json", action="store_true",
                    help="emit the structured diff document instead of "
                         "the rendered table")
    args = ap.parse_args(argv)

    costs = load_costs_module()
    docs = []
    for path in (args.current, args.baseline):
        try:
            with open(path) as f:
                docs.append(json.load(f))
        except OSError as e:
            print(f"cost_diff: cannot read {path}: {e}", file=sys.stderr)
            return 2
        except ValueError as e:
            print(f"cost_diff: {path} is not JSON: {e}", file=sys.stderr)
            return 2
    cur, base = docs
    reasons = costs.provenance_mismatch(cur, base)
    if reasons:
        # diffing incomparable ledgers would attribute the workload
        # delta (a different mesh, dtype, or slot count) to code — the
        # check_regression refusal discipline, loudly
        for reason in reasons:
            print(f"cost_diff: INCOMPARABLE — {reason}", file=sys.stderr)
        return 2
    diff = costs.diff_ledgers(cur, base)
    if args.json:
        json.dump(diff, sys.stdout, sort_keys=True, indent=1,
                  default=float)
        sys.stdout.write("\n")
    else:
        for line in render(diff):
            print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
