#!/usr/bin/env python
"""Merge per-rank/per-run metrics snapshots into one fleet view.

Every serving/training process writes one mergeable snapshot
(``apex-tpu-serve --metrics-snapshot``, ``apex-tpu-bench --serve
--metrics-snapshot``, or a scrape of ``/metrics.json``). Because every
histogram everywhere shares the same fixed log-bucket boundaries
(``apex_tpu/monitor/export.py``), folding N snapshots is **exact**:
counters add, gauges combine by their declared aggregation, histogram
buckets add — bit-identical to having recorded the union stream into one
registry. This is the aggregation seam tensor-parallel serving ranks
will merge through (ROADMAP item 1).

Usage::

    python tools/metrics_merge.py rank0.json rank1.json ... -o fleet.json
    python tools/metrics_merge.py rank*.json --prometheus   # text to stdout

Exit status: 0 merged, 2 usage error (missing file, not a snapshot,
incompatible histogram geometry — merging incomparable captures would
silently fabricate a fleet view, so it refuses loudly instead).

This tool is **standalone**: it loads the export module by file path, so
it runs on a machine with no jax installed (the fleet-aggregation box is
rarely an accelerator host).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_export_module():
    """Load ``apex_tpu/monitor/export.py`` WITHOUT importing the
    ``apex_tpu`` package (whose __init__ pulls jax): the module is
    deliberately stdlib-only at import time for exactly this caller."""
    path = os.path.join(_REPO, "apex_tpu", "monitor", "export.py")
    spec = importlib.util.spec_from_file_location(
        "_apex_tpu_metrics_export", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge mergeable metrics snapshots into one fleet "
                    "view (counters add, gauges combine by declared agg, "
                    "histogram buckets add exactly)")
    ap.add_argument("snapshots", nargs="+",
                    help="per-rank/per-run snapshot JSON files")
    ap.add_argument("-o", "--output", default=None,
                    help="write the merged snapshot here (atomic .tmp + "
                         "os.replace; default: stdout)")
    ap.add_argument("--prometheus", action="store_true",
                    help="render the merged view as Prometheus text "
                         "exposition instead of snapshot JSON")
    args = ap.parse_args(argv)

    export = load_export_module()
    docs = []
    for path in args.snapshots:
        try:
            with open(path) as f:
                docs.append(json.load(f))
        except OSError as e:
            print(f"metrics_merge: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
        except ValueError as e:
            print(f"metrics_merge: {path} is not JSON: {e}",
                  file=sys.stderr)
            return 2
    try:
        merged = export.merge_snapshots(docs)
    except ValueError as e:
        # wrong schema / type mismatch / histogram geometry mismatch:
        # these snapshots are NOT mergeable and a fabricated fleet view
        # would be worse than no view
        print(f"metrics_merge: {e}", file=sys.stderr)
        return 2
    if args.prometheus:
        text = export.snapshot_to_prometheus(merged)
        if args.output:
            export.atomic_write_text(args.output, text)
        else:
            sys.stdout.write(text)
        return 0
    if args.output:
        export.atomic_write_json(args.output, merged)
    else:
        json.dump(merged, sys.stdout, sort_keys=True, indent=1,
                  default=float)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
