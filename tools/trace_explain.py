#!/usr/bin/env python
"""Merge fleet + per-replica request-journey traces into per-request
latency attribution — and verify it reconciles EXACTLY with the fleet
summary and the goodput ledger's timed causes.

Input: the Chrome-trace files a fleet run writes (``apex-tpu-serve
--replicas N --trace-jsonl PATH`` → ``PATH`` fleet plane + ``PATH.rK``
per replica; single-scheduler traces work too), plus optionally:

- ``--events telemetry.jsonl`` — the ``--telemetry-jsonl`` event mirror:
  the ledger's serve timed causes (``serve_failover``, queue waits, ...)
  are recomputed from it and held against the failover spans' ``seconds``
  attrs (the SAME rounded values — exact, not approximate);
- ``--summary summary.json`` — the CLI's final JSON line (or just its
  ``summary`` object): journey counts, terminal states,
  failover/hedge/migration/retry counters, and the TTFT percentiles are
  reconciled bit-for-bit (journey ttfts ARE the record values the summary
  computed from).

Output: top-K slowest requests with their dominant latency cause
(queue / prefill / decode / fleet_queue / backoff / failover), one line
each, then the reconciliation verdict. ``--perfetto OUT.json`` emits a
merged Chrome-trace view with **one track per replica** (plus the fleet
plane) — the side-by-side rendering of a request hopping replicas that
per-file traces cannot show. ``--json`` prints the attribution rows as
JSON instead of text.

Head-sampled captures (``--trace-sample`` < 1) are detected from the
summary's ``trace`` block (or forced with ``--sampled``): checks that
need EVERY journey present are skipped; the ledger/failover checks still
run — tail capture promises bad-outcome journeys are always captured.

Exit status: 0 reconciled (or nothing to reconcile against), 1 any
mismatch — the reconciliation IS the test — 2 usage error.

This tool is **standalone**: it loads ``apex_tpu/monitor/journey.py`` by
file path (the ``metrics_merge.py`` pattern), so it runs on a machine
with no jax installed.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_journey_module():
    """Load ``apex_tpu/monitor/journey.py`` WITHOUT importing the
    ``apex_tpu`` package (whose __init__ pulls jax): the module is
    deliberately stdlib-only at import time for exactly this caller."""
    path = os.path.join(_REPO, "apex_tpu", "monitor", "journey.py")
    spec = importlib.util.spec_from_file_location(
        "_apex_tpu_journey", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fmt_row(j: dict) -> str:
    parts = []
    for key, label in (("fleet_queue_s", "fleet_queue"),
                       ("queue_s", "queue"), ("prefill_s", "prefill"),
                       ("decode_s", "decode"), ("backoff_s", "backoff"),
                       ("failover_lost_s", "failover")):
        v = j.get(key) or 0.0
        if v > 0:
            parts.append(f"{label}={v * 1e3:.3f}ms")
    extras = []
    if j.get("hedged"):
        extras.append("hedged")
    if j.get("failovers"):
        extras.append(f"failovers={j['failovers']}")
    if j.get("retries"):
        extras.append(f"retries={j['retries']}")
    lat = (j.get("latency_s") or 0.0) * 1e3
    return (f"{j['request_id']:>12s}  {lat:9.3f}ms  "
            f"{j['state'] or '?':>9s}  dominant={j['dominant']:<15s} "
            f"{' '.join(parts)}"
            + (f"  [{' '.join(extras)}]" if extras else ""))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge fleet + per-replica trace files into "
                    "per-request latency attribution and verify it "
                    "reconciles with the summary and the ledger")
    ap.add_argument("traces", nargs="+",
                    help="Chrome-trace files (the fleet PATH plus every "
                         "PATH.rK)")
    ap.add_argument("--events", default=None,
                    help="--telemetry-jsonl event mirror: reconcile the "
                         "failover spans against the ledger's timed "
                         "causes")
    ap.add_argument("--summary", default=None,
                    help="the CLI's final JSON line (or its summary "
                         "object): reconcile counts + TTFT percentiles")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest requests to print (default 10)")
    ap.add_argument("--perfetto", default=None,
                    help="write the merged Chrome-trace view here (one "
                         "track per replica + the fleet plane)")
    ap.add_argument("--json", action="store_true",
                    help="print attribution rows as JSON, not text")
    ap.add_argument("--sampled", action="store_true",
                    help="the capture was head-sampled: skip the checks "
                         "that need every journey present (auto-detected "
                         "from the summary's trace block)")
    ap.add_argument("--tolerance", type=float, default=2e-3,
                    help="stamp-rounding tolerance in seconds for span "
                         "SUM checks (attr-based checks stay exact; "
                         "default 2e-3)")
    args = ap.parse_args(argv)

    journey = load_journey_module()

    for path in args.traces:
        if not os.path.exists(path):
            print(f"trace_explain: no such file: {path}",
                  file=sys.stderr)
            return 2
    try:
        records = journey.load_trace_files(args.traces)
    except ValueError as e:
        print(f"trace_explain: {e}", file=sys.stderr)
        return 2

    summary = None
    complete = not args.sampled
    if args.summary:
        try:
            with open(args.summary) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"trace_explain: cannot read --summary: {e}",
                  file=sys.stderr)
            return 2
        summary = doc.get("summary", doc)
        if not isinstance(summary, dict) or "requests" not in summary:
            print(f"trace_explain: {args.summary} is not a serve "
                  f"summary (want the CLI's final JSON line or its "
                  f"'summary' object)", file=sys.stderr)
            return 2
        trace_meta = doc.get("trace")
        if isinstance(trace_meta, dict) \
                and float(trace_meta.get("sample_rate", 1.0)) < 1.0:
            complete = False

    causes = counts = None
    if args.events:
        try:
            events = journey.read_events_jsonl(args.events)
        except (OSError, ValueError) as e:
            print(f"trace_explain: cannot read --events: {e}",
                  file=sys.stderr)
            return 2
        causes, counts = journey.ledger_causes(events)

    journeys = journey.attribute_journeys(records)
    if not journeys:
        print("trace_explain: no request journeys in the given traces "
              "(were they written with --trace-jsonl?)", file=sys.stderr)
        return 2

    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump(journey.merged_perfetto(records), f)
        print(f"trace_explain: merged Perfetto view -> {args.perfetto} "
              f"(one track per replica)", file=sys.stderr)

    top = journey.top_slowest(journeys, args.top)
    if args.json:
        print(json.dumps({"journeys": journeys, "top": top},
                         sort_keys=True, default=float))
    else:
        print(f"{len(journeys)} journeys; top {len(top)} slowest:")
        for j in top:
            print(_fmt_row(j))

    problems = journey.reconcile(
        journeys, records, summary=summary, causes=causes,
        counts=counts, stamp_tol_s=args.tolerance,
        complete_capture=complete)
    if problems:
        for p in problems:
            print(f"MISMATCH: {p}", file=sys.stderr)
        print(f"trace_explain: {len(problems)} reconciliation "
              f"mismatch(es) — span attribution does not agree with "
              f"the summary/ledger accounting", file=sys.stderr)
        return 1
    if summary is None and causes is None:
        print("trace_explain: attribution only (pass --summary/--events "
              "to reconcile)", file=sys.stderr)
    else:
        checked = []
        if summary is not None:
            checked.append("summary" + ("" if complete
                                        else " (sampled subset)"))
        if causes is not None:
            checked.append("ledger causes")
        print(f"trace_explain: reconciled against {' + '.join(checked)}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
